"""Sharding & mesh contracts (skycheck pass ``shard``): prove the TP
plane's layouts before scaling it.

The mesh vocabulary lives in ONE place (``parallel/mesh.py``:
``MESH_AXES`` + ``_BASE_RULES``), but PartitionSpecs, logical-axis
tuples and ``axis_name=`` strings are scattered across the engine, the
model, the trainer and the collective kernels — an axis rename (or a
typo'd logical axis) silently resolves to *replicated*, which on a
``tensor>1`` mesh is an HBM blow-up, not an error.  This pass parses
the vocabulary straight out of ``parallel/mesh.py`` (pure ast — no jax
import) and checks every sharding-bearing construct in the mesh-using
modules against it, plus a declarative registry of the big buffers and
the divisibility proofs their sharded dims need:

- **SHARD001** — a ``PartitionSpec`` / ``axis_name=`` / logical-axis
  string names an axis the constructed mesh (``MESH_AXES``) or the
  logical rule table does not define.  First-match rule resolution
  makes unknown names *silently replicate*; this makes them loud.
- **SHARD002** — a registry-declared large buffer (KV cache, params)
  reaches a ``jax.jit`` root with **no** sharding application anywhere
  on its def-chain while the module constructs a mesh: the
  fully-replicated HBM blow-up that blocks the sharded KV pool.
- **SHARD003** — a host transfer (``np.asarray`` / ``.item()`` /
  ``jax.device_get`` / implicit bool) on a value whose def-chain
  carries an explicit ``NamedSharding`` — reusing the JIT001 sync
  catalogue: gathering a sharded array to host is a cross-device
  all-gather hidden inside a cast.
- **SHARD004** — a registry-declared sharded dim whose symbolic size
  (``num_kv_heads``-style, the same symbols the compile pass's bucket
  lattice resolves) has no divisibility guard (``sym % axis_size``)
  against the mesh axis it shards over, and no ``# shard-spec:``
  assertion standing in for one.

Escape hatches (plain line comments, reviewed like code):

- ``# shard-ok: <reason>`` — suppress any SHARD finding on that line.
- ``# shard-spec: SYM % AXIS`` — asserts SYM is divisible by the size
  of mesh axis AXIS (satisfies SHARD004 where the guard lives behind
  an abstraction the dataflow cannot see through).  The runtime shard
  sanitizer (``SKYTPU_SHARD_SANITIZER``, analysis/sanitizers.py) will
  catch a lie the same way the compile sanitizer does.

The registry (``REGISTRY``) is the certified substrate ROADMAP item 2
shards the paged KV pool against: per module, the mesh attribute, the
large buffers with their declared logical specs, and the divisibility
contracts.  ``declared_specs()`` exports it for the docs table and the
tier-1 snapshot test; ``render_markdown()`` generates the
sharding-contract table in docs/architecture.md.
"""
import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import compile_budget, dataflow
from skypilot_tpu.analysis.findings import Finding

PASS_UNKNOWN_AXIS = 'SHARD001'
PASS_REPLICATED_BUFFER = 'SHARD002'
PASS_HOST_TRANSFER = 'SHARD003'
PASS_INDIVISIBLE_DIM = 'SHARD004'

# The single source of truth for the mesh vocabulary.
MESH_FILE = 'skypilot_tpu/parallel/mesh.py'

# Mesh-using modules the pass sweeps (plus any file in REGISTRY).
SHARD_FILES = frozenset({
    'skypilot_tpu/infer/engine.py',
    'skypilot_tpu/models/llama.py',
    'skypilot_tpu/train/trainer.py',
    'skypilot_tpu/parallel/mesh.py',
    'skypilot_tpu/parallel/pipeline.py',
    'skypilot_tpu/ops/flash_attention.py',
    'skypilot_tpu/ops/ring_attention.py',
})

# Fallback vocabulary for unit fixtures that do not ship a mesh.py.
DEFAULT_MESH_AXES = ('stage', 'data', 'fsdp', 'seq', 'tensor')
DEFAULT_LOGICAL_AXES = frozenset({
    'batch', 'activation_batch', 'activation_seq', 'activation_embed',
    'activation_heads', 'activation_kv', 'activation_mlp', 'embed',
    'mlp', 'heads', 'kv_heads', 'qkv_embed', 'vocab', 'vocab_table',
    'embed_table', 'expert', 'norm',
})

_OK_RE = re.compile(r'#\s*shard-ok\b')
_SPEC_RE = re.compile(r'#\s*shard-spec:\s*(\w+)\s*%\s*(\w+)')

# Parameter names whose tuple-of-string arguments are LOGICAL axes.
_AXES_PARAMS = frozenset({'axes', 'kernel_axes', 'logical_axes'})

# Call forms whose string arguments are MESH axes (positional index of
# the axis-name argument).
_MESH_AXIS_CALLS = {
    'axis_size': 0, 'axis_index': 0, 'ppermute': 1, 'pshuffle': 1,
}

# Fresh large allocations (the unsharded-def classifier for SHARD002).
_ALLOC_CALLS = frozenset({
    'init_cache', 'init_paged_cache', 'zeros', 'ones', 'full', 'empty',
})

# Host-transfer catalogue — the JIT001 sync set (jit_boundary.py).
_HOST_CALLS = frozenset({
    'np.asarray', 'np.array', 'numpy.asarray', 'numpy.array',
    'jax.device_get',
})
_HOST_METHODS = frozenset({'item', 'tolist', 'block_until_ready'})


@dataclasses.dataclass(frozen=True)
class BufferSpec:
    """One registry-declared large buffer.

    spec: declared logical axes per dim (None = replicated dim), or
    None meaning "per-leaf via logical_axis_rules" (a param pytree).
    divisibility: (symbol, mesh_axis) contracts — the symbol's size
    must be guarded divisible by the axis size wherever the buffer is
    sharded over it.
    """
    name: str
    spec: Optional[Tuple[Optional[str], ...]]
    divisibility: Tuple[Tuple[str, str], ...] = ()
    # attr: the ``self.<attr>`` holding the buffer when it differs from
    # the display name (two registry rows may prove two allocation
    # paths of ONE attribute — e.g. the dense cache and the paged
    # pool both live in ``self.cache``).
    attr: Optional[str] = None
    # alloc: anchor the SHARD002 proof to the functions that build the
    # buffer through THIS allocation call (``init_paged_cache``-style).
    # Without it, any sharding-applying def anywhere satisfies the
    # check; with it, every function containing the anchor allocation
    # must itself carry a sharding-applying def of the attr — so the
    # paged pool's layout is proven independently of the dense path.
    alloc: Optional[str] = None

    @property
    def attr_name(self) -> str:
        return self.attr or self.name

    def spec_str(self) -> str:
        if self.spec is None:
            return 'logical_axis_rules (per-leaf, mesh-fitted)'
        return 'P(' + ', '.join('None' if a is None else a
                                for a in self.spec) + ')'


@dataclasses.dataclass(frozen=True)
class ModuleContract:
    """Declared sharding contract of one mesh-using module."""
    mesh_attr: str
    buffers: Tuple[BufferSpec, ...]


# The declarative registry: the certified substrate the TP plane (and
# ROADMAP item 2's sharded KV pool) is checked against.  cache is
# [B,Hkv,S,D] dense / [N,Hkv,bs,D] paged — kv-heads on dim 1 either
# way, sharded like the weights' 'kv_heads' logical axis; params are
# born sharded per-leaf through the logical rule table and fitted to
# the mesh (indivisible dims replicate, see engine._fit_sharding).
REGISTRY: Dict[str, ModuleContract] = {
    'skypilot_tpu/infer/engine.py': ModuleContract(
        mesh_attr='_mesh',
        buffers=(
            BufferSpec('cache', (None, 'kv_heads', None, None),
                       divisibility=(('num_kv_heads', 'tensor'),)),
            # The paged block pool ([num_blocks, Hkv, block_size, D]
            # per layer) shares self.cache with the dense layout but
            # gets its OWN proof row anchored on init_paged_cache: the
            # function (re)building the pool must apply the head
            # sharding itself, so dropping the device_put from the
            # paged branch can never hide behind the dense path's.
            BufferSpec('cache[paged pool]',
                       (None, 'kv_heads', None, None),
                       divisibility=(('num_kv_heads', 'tensor'),),
                       attr='cache', alloc='init_paged_cache'),
            BufferSpec('params', None),
        ),
    ),
}


def declared_specs() -> Dict[str, Dict[str, str]]:
    """Registry export for the docs table and the tier-1 snapshot
    test: {module: {buffer: declared spec string}}."""
    return {
        path: {b.name: b.spec_str() for b in mc.buffers}
        for path, mc in sorted(REGISTRY.items())
    }


# --------------------------------------------------------- vocabulary

def mesh_vocabulary(mesh_text: Optional[str]):
    """Parse (MESH_AXES, logical-axis names, rule entries) out of
    parallel/mesh.py.  rule entries are (logical, target, line) with
    target a mesh axis string, tuple of them, or None."""
    if mesh_text is None:
        return DEFAULT_MESH_AXES, set(DEFAULT_LOGICAL_AXES), []
    tree = ast.parse(mesh_text)
    axes: Tuple[str, ...] = ()
    rules: List[Tuple[str, object, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, (ast.Assign, ast.AnnAssign))):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        value = node.value
        if value is None:
            continue
        if 'MESH_AXES' in names and isinstance(value, ast.Tuple):
            axes = tuple(e.value for e in value.elts
                         if isinstance(e, ast.Constant) and
                         isinstance(e.value, str))
        if '_BASE_RULES' in names and isinstance(value, ast.List):
            for elt in value.elts:
                if not (isinstance(elt, ast.Tuple) and
                        len(elt.elts) == 2 and
                        isinstance(elt.elts[0], ast.Constant)):
                    continue
                tgt = elt.elts[1]
                if isinstance(tgt, ast.Constant):
                    target = tgt.value          # str or None
                elif isinstance(tgt, ast.Tuple):
                    target = tuple(e.value for e in tgt.elts
                                   if isinstance(e, ast.Constant))
                else:
                    continue
                rules.append((elt.elts[0].value, target, elt.lineno))
    if not axes:
        axes = DEFAULT_MESH_AXES
    logical = {name for name, _, _ in rules} or set(DEFAULT_LOGICAL_AXES)
    return axes, logical, rules


# --------------------------------------------------------- ast helpers

def _last_seg(name: Optional[str]) -> Optional[str]:
    return None if name is None else name.rsplit('.', 1)[-1]


def _str_elems(node: ast.AST) -> List[Tuple[str, int]]:
    """String literals directly inside a constant/tuple/list expression
    (ints, None and unresolvable names are skipped)."""
    out: List[Tuple[str, int]] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append((node.value, node.lineno))
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append((e.value, e.lineno))
            elif isinstance(e, (ast.Tuple, ast.List)):
                out.extend(_str_elems(e))
    return out


def _partitionspec_aliases(tree: ast.AST) -> Set[str]:
    """Names bound to jax.sharding.PartitionSpec anywhere in the module
    (``P = jax.sharding.PartitionSpec``, import aliases, function-local
    ``p = ...`` included — collisions are unlikely and conservative)."""
    aliases = {'PartitionSpec'}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                _last_seg(dataflow.dotted_name(node.value)) == \
                'PartitionSpec':
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    aliases.add(tgt.id)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == 'PartitionSpec' and a.asname:
                    aliases.add(a.asname)
    return aliases


def _is_sharding_apply(expr: ast.AST) -> bool:
    """True when the expression applies an explicit sharding anywhere
    inside it: ``jax.device_put(x, sharding)`` (2-arg form),
    ``with_sharding_constraint``, ``named_sharding(...)``, or a
    ``jax.jit(..., out_shardings=...)``."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        last = _last_seg(dataflow.dotted_name(node.func))
        if last == 'device_put' and len(node.args) >= 2 and not (
                isinstance(node.args[1], ast.Constant) and
                node.args[1].value is None):
            return True
        if last in ('with_sharding_constraint', 'named_sharding'):
            return True
        if last == 'jit' and any(kw.arg in ('out_shardings',
                                            'in_shardings')
                                 for kw in node.keywords):
            return True
    return False


def _sharding_methods(index: dataflow.ModuleIndex) -> Set[str]:
    """Simple names of module functions whose body applies a sharding
    (one interprocedural level: ``self.params = self._shard(...)``)."""
    out: Set[str] = set()
    for qual, info in index.functions.items():
        if _is_sharding_apply(info.node):
            out.add(qual.rsplit('.', 1)[-1])
    return out


def _scopes(index: dataflow.ModuleIndex) -> List[ast.AST]:
    """Every function node plus the module for top-level statements."""
    return [info.node for info in index.functions.values()]


def _sharded_locals(fn_node: ast.AST, methods: Set[str]) -> Set[str]:
    """Local names with at least one sharding-applying definition."""
    out: Set[str] = set()
    for name, exprs in dataflow.local_defs(fn_node).items():
        for expr in exprs:
            if _is_sharding_apply(expr):
                out.add(name)
                break
            call = expr
            if isinstance(call, ast.Call):
                last = _last_seg(dataflow.dotted_name(call.func))
                if last in methods:
                    out.add(name)
                    break
    return out


# ------------------------------------------------------------ checks

def _check_module(rel: str, text: str, mesh_axes: Sequence[str],
                  logical_axes: Set[str],
                  contract: Optional[ModuleContract]) -> List[Finding]:
    try:
        index = dataflow.ModuleIndex(rel, text)
    except SyntaxError:
        return []
    lines = index.lines
    findings: List[Finding] = []

    def ok(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and \
            bool(_OK_RE.search(lines[lineno - 1]))

    def mesh_check(ax: str, lineno: int, ctx: str) -> None:
        if ax not in mesh_axes and not ok(lineno):
            findings.append(Finding(
                rel, lineno, PASS_UNKNOWN_AXIS,
                f"{ctx} names mesh axis '{ax}' which no constructed "
                f'Mesh defines (MESH_AXES={tuple(mesh_axes)}); it '
                'would silently resolve to replicated'))

    def logical_check(ax: str, lineno: int, ctx: str) -> None:
        if ax not in logical_axes and not ok(lineno):
            findings.append(Finding(
                rel, lineno, PASS_UNKNOWN_AXIS,
                f"{ctx} names logical axis '{ax}' with no rule in "
                "parallel/mesh.py logical_axis_rules; first-match "
                'resolution silently replicates it'))

    ps_aliases = _partitionspec_aliases(index.tree)

    for node in ast.walk(index.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # str default of a parameter named axis_name is a mesh axis
            # (the collective kernels' calling convention).
            args = node.args
            pos = args.posonlyargs + args.args
            for arg, dflt in zip(pos[len(pos) - len(args.defaults):],
                                 args.defaults):
                if arg.arg == 'axis_name' and \
                        isinstance(dflt, ast.Constant) and \
                        isinstance(dflt.value, str):
                    mesh_check(dflt.value, dflt.lineno,
                               f"default of '{node.name}(axis_name=)'")
            continue
        if not isinstance(node, ast.Call):
            continue
        callee = dataflow.dotted_name(node.func)
        last = _last_seg(callee)
        if last in ps_aliases:
            for arg in node.args:
                for ax, ln in _str_elems(arg):
                    mesh_check(ax, ln, 'PartitionSpec')
        elif last == 'named_sharding':
            for arg in node.args[1:]:
                for ax, ln in _str_elems(arg):
                    logical_check(ax, ln, 'named_sharding')
        elif last in ('with_logical_constraint',
                      'with_logical_partitioning'):
            if len(node.args) >= 2:
                for ax, ln in _str_elems(node.args[1]):
                    logical_check(ax, ln, last)
        elif last in _MESH_AXIS_CALLS:
            idx = _MESH_AXIS_CALLS[last]
            if len(node.args) > idx and \
                    isinstance(node.args[idx], ast.Constant) and \
                    isinstance(node.args[idx].value, str):
                mesh_check(node.args[idx].value, node.args[idx].lineno,
                           f'{last}()')
        for kw in node.keywords:
            if kw.arg == 'axis_name' and \
                    isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                mesh_check(kw.value.value, kw.value.lineno,
                           f'{last or "call"}(axis_name=)')
            elif kw.arg in _AXES_PARAMS:
                for ax, ln in _str_elems(kw.value):
                    logical_check(ax, ln, f'{last or "call"}'
                                          f'({kw.arg}=)')
        # Positional tuple-of-str args binding to a module-local
        # function's parameter named axes/kernel_axes/logical_axes.
        if last in {q.rsplit('.', 1)[-1] for q in index.functions}:
            info = index.find(last)
            if info is not None:
                params = info.params
                if params and params[0] == 'self':
                    params = params[1:]
                for i, arg in enumerate(node.args):
                    if i < len(params) and params[i] in _AXES_PARAMS:
                        for ax, ln in _str_elems(arg):
                            logical_check(
                                ax, ln,
                                f'{last}({params[i]}=)')

    if contract is not None:
        findings.extend(_check_contract(rel, text, index, contract,
                                        mesh_axes, ok))
    findings.extend(_check_host_transfers(rel, index, contract, ok))
    return findings


def _attr_defs(index: dataflow.ModuleIndex,
               attr: str) -> List[Tuple[ast.expr, int, ast.AST]]:
    """Every ``self.<attr> = <expr>`` in the module: (expr, line,
    enclosing function node)."""
    out = []
    for info in index.functions.values():
        for node in dataflow._walk_no_nested(info.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == 'self' and tgt.attr == attr:
                        out.append((node.value, node.lineno, info.node))
    return out


def _check_contract(rel: str, text: str, index: dataflow.ModuleIndex,
                    contract: ModuleContract,
                    mesh_axes: Sequence[str], ok) -> List[Finding]:
    findings: List[Finding] = []
    has_mesh = bool(re.search(
        rf'self\.{re.escape(contract.mesh_attr)}\b', text))
    if not has_mesh:
        return findings
    methods = _sharding_methods(index)
    roots = {r.name for r in compile_budget.discover_roots(text)}
    spec_annots = {(m.group(1), m.group(2))
                   for m in _SPEC_RE.finditer(text)}

    # Which buffers are passed to a jit root call (self._root(...)).
    root_args: Set[str] = set()
    for node in ast.walk(index.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == 'self' and node.func.attr in roots:
            for arg in node.args:
                name = dataflow.dotted_name(arg)
                if name and name.startswith('self.'):
                    root_args.add(name.split('.')[1])
                elif isinstance(arg, ast.Name):
                    root_args.add(arg.id)

    # SHARD002: a registry buffer with defs but no sharding-applying
    # def anywhere, reaching a jit root, in a mesh-bearing module.
    # An alloc-anchored buffer narrows the proof to the functions that
    # actually build it through that allocation call (the paged pool's
    # init_paged_cache), so one path's device_put cannot vouch for
    # another's.
    for buf in contract.buffers:
        defs = _attr_defs(index, buf.attr_name)
        if not defs or buf.attr_name not in root_args:
            continue
        if buf.alloc is not None:
            alloc_fns = {
                id(fn_node) for expr, _, fn_node in defs
                if any(isinstance(c, ast.Call) and
                       _last_seg(dataflow.dotted_name(c.func)) ==
                       buf.alloc
                       for c in ast.walk(expr))}
            defs = [d for d in defs if id(d[2]) in alloc_fns]
            if not defs:
                continue
        sharded = False
        for expr, _, fn_node in defs:
            if _is_sharding_apply(expr):
                sharded = True
                break
            if isinstance(expr, ast.Call):
                last = _last_seg(dataflow.dotted_name(expr.func))
                if last in methods:
                    sharded = True
                    break
            if isinstance(expr, ast.Name) and \
                    expr.id in _sharded_locals(fn_node, methods):
                sharded = True
                break
        if not sharded and not any(ok(line) for _, line, _ in defs):
            findings.append(Finding(
                rel, defs[0][1], PASS_REPLICATED_BUFFER,
                f"large buffer 'self.{buf.attr_name}' "
                f"(registry row '{buf.name}') reaches jit root(s) "
                f'with no sharding application on any def while this '
                f'module constructs a mesh (declared spec '
                f'{buf.spec_str()}): fully replicated under tensor>1 '
                'is an HBM blow-up'))

    # SHARD004: declared divisibility contracts need a `sym % axis`
    # guard (or a # shard-spec: assertion).  Only meaningful when the
    # module actually applies shardings.
    apply_lines = [node.lineno for node in ast.walk(index.tree)
                   if isinstance(node, ast.Call) and
                   _is_sharding_apply(node)]
    if not apply_lines:
        return _dedup(findings)
    axis_vars = _axis_size_vars(index.tree, mesh_axes)
    guards = _divisibility_guards(index.tree, axis_vars)
    for buf in contract.buffers:
        for sym, axis in buf.divisibility:
            if (sym, axis) in spec_annots or (sym, axis) in guards:
                continue
            line = min(apply_lines)
            if ok(line):
                continue
            findings.append(Finding(
                rel, line, PASS_INDIVISIBLE_DIM,
                f"buffer '{buf.name}' shards symbolic dim '{sym}' over "
                f"mesh axis '{axis}' with no divisibility guard "
                f"('{sym} % <{axis} size>' check) and no "
                f"'# shard-spec: {sym} % {axis}' assertion: an "
                'indivisible dim silently replicates (or mis-shards) '
                'at placement'))
    return _dedup(findings)


def _dedup(findings: List[Finding]) -> List[Finding]:
    """Two registry rows proving one attribute (dense cache + paged
    pool) can flag the same defect line twice; one finding per
    (line, pass) is enough for the ratchet."""
    deduped: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for f in findings:
        key = (f.path, f.line, f.pass_id)
        if key not in seen:
            seen.add(key)
            deduped.append(f)
    return deduped


def _axis_size_vars(tree: ast.AST,
                    mesh_axes: Sequence[str]) -> Dict[str, str]:
    """Local/attr names holding a mesh-axis size: assigned from
    ``....get('<axis>', ...)``, ``...shape['<axis>']`` or
    ``lax.axis_size('<axis>')``."""
    out: Dict[str, str] = {}

    def axis_of(expr: ast.AST) -> Optional[str]:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                last = node.func.attr \
                    if isinstance(node.func, ast.Attribute) \
                    else _last_seg(dataflow.dotted_name(node.func))
                if last in ('get', 'axis_size') and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value in mesh_axes:
                    return node.args[0].value
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.slice, ast.Constant) and \
                    node.slice.value in mesh_axes:
                name = dataflow.dotted_name(node.value)
                if name and name.endswith('shape'):
                    return node.slice.value
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        axis = axis_of(node.value)
        if axis is None:
            continue
        for tgt in node.targets:
            name = dataflow.dotted_name(tgt)
            if name:
                out[name] = axis
    return out


def _divisibility_guards(tree: ast.AST,
                         axis_vars: Dict[str, str]
                         ) -> Set[Tuple[str, str]]:
    """(symbol, axis) pairs guarded by a ``sym % axis_size_var``
    expression anywhere in the module (if-tests, asserts, raises)."""
    guards: Set[Tuple[str, str]] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.BinOp) and
                isinstance(node.op, ast.Mod)):
            continue
        left = dataflow.dotted_name(node.left)
        if left is None:
            continue
        # The divisor may be wrapped (max(tp, 1)): any axis-size name
        # anywhere inside the right operand counts.
        for sub in ast.walk(node.right):
            name = dataflow.dotted_name(sub)
            axis = axis_vars.get(name) if name else None
            if axis is not None:
                guards.add((left.rsplit('.', 1)[-1], axis))
    return guards


def _check_host_transfers(rel: str, index: dataflow.ModuleIndex,
                          contract: Optional[ModuleContract],
                          ok) -> List[Finding]:
    """SHARD003: host transfers on values whose def-chain carries an
    explicit sharding application."""
    findings: List[Finding] = []
    methods = _sharding_methods(index)
    sharded_attrs: Set[str] = set()
    if contract is not None:
        for buf in contract.buffers:
            for expr, _, fn_node in _attr_defs(index, buf.attr_name):
                if _is_sharding_apply(expr):
                    sharded_attrs.add(buf.attr_name)
                    break

    def is_sharded(expr: ast.AST, local: Set[str]) -> bool:
        name = dataflow.dotted_name(expr)
        if name is None:
            return False
        if name in local:
            return True
        parts = name.split('.')
        return len(parts) >= 2 and parts[0] == 'self' and \
            parts[1] in sharded_attrs

    def flag(lineno: int, what: str) -> None:
        if not ok(lineno):
            findings.append(Finding(
                rel, lineno, PASS_HOST_TRANSFER,
                f'{what} on a value whose def-chain carries a '
                'NamedSharding: a host transfer of a device-sharded '
                'array is a hidden cross-device all-gather (annotate '
                '# shard-ok: <reason> if the gather is intended)'))

    for info in index.functions.values():
        local = _sharded_locals(info.node, methods)
        if not local and not sharded_attrs:
            continue
        for node in dataflow._walk_no_nested(info.node):
            if isinstance(node, ast.Call):
                callee = dataflow.dotted_name(node.func)
                last = _last_seg(callee)
                if (callee in _HOST_CALLS or last == 'device_get') \
                        and node.args and \
                        is_sharded(node.args[0], local):
                    flag(node.lineno, f'{callee or last}()')
                elif last in _HOST_METHODS and \
                        isinstance(node.func, ast.Attribute) and \
                        is_sharded(node.func.value, local):
                    flag(node.lineno, f'.{last}()')
                elif last in ('bool', 'float', 'int') and node.args \
                        and is_sharded(node.args[0], local):
                    flag(node.lineno, f'{last}()')
            elif isinstance(node, (ast.If, ast.While)) and \
                    is_sharded(node.test, local):
                flag(node.test.lineno, 'implicit bool')
    return findings


# ------------------------------------------------------------- driver

def check_tree(files: Dict[str, str],
               registry: Optional[Dict[str, ModuleContract]] = None
               ) -> List[Finding]:
    """The skycheck ``shard`` tree pass: vocabulary from mesh.py, then
    every mesh-using module checked against it + the registry."""
    if registry is None:
        registry = REGISTRY
    mesh_axes, logical_axes, rules = mesh_vocabulary(
        files.get(MESH_FILE))
    findings: List[Finding] = []
    # Rule-target drift inside the vocabulary itself: a _BASE_RULES
    # entry mapping to an axis MESH_AXES does not define.
    for name, target, line in rules:
        targets = target if isinstance(target, tuple) else (target,)
        for ax in targets:
            if ax is not None and ax not in mesh_axes:
                findings.append(Finding(
                    MESH_FILE, line, PASS_UNKNOWN_AXIS,
                    f"logical rule '{name}' maps to mesh axis '{ax}' "
                    f'which MESH_AXES does not define '
                    f'({tuple(mesh_axes)})'))
    for rel in sorted(files):
        if rel not in SHARD_FILES and rel not in registry:
            continue
        findings.extend(_check_module(rel, files[rel], mesh_axes,
                                      logical_axes,
                                      registry.get(rel)))
    return findings


def render_markdown(files: Dict[str, str]) -> str:
    """The generated sharding-contract table for docs/architecture.md."""
    mesh_axes, _, rules = mesh_vocabulary(files.get(MESH_FILE))
    rows = ['| module | buffer | declared spec (logical axes) | '
            'divisibility contract |',
            '|---|---|---|---|']
    for path, mc in sorted(REGISTRY.items()):
        for buf in mc.buffers:
            div = ', '.join(f'`{s} % {a}`' for s, a in buf.divisibility)
            rows.append(f'| `{path}` | `{buf.name}` | '
                        f'`{buf.spec_str()}` | {div or "—"} |')
    header = (f'Mesh axes: `{tuple(mesh_axes)}`; '
              f'{len(rules)} logical-axis rules '
              '(`parallel/mesh.py:_BASE_RULES`).\n\n')
    return header + '\n'.join(rows) + '\n'
