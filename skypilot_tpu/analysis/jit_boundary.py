"""JIT pass: host-sync hygiene on the jitted dispatch paths.

On TPUs the silent hot-path killers are host-device synchronization
(``.item()``, ``np.asarray`` on a device array, ``jax.device_get``,
``block_until_ready``) and recompilation from Python-varying shapes.
This pass builds a per-class call graph (``self.<meth>()`` edges) from
configured hot roots — the engine's decode/prefill dispatch methods —
and inside every reachable method flags:

- JIT001: host-sync calls (``np.asarray``/``np.array``, ``.item()``,
  ``jax.device_get``, ``.block_until_ready()``).  A known-cold call
  site (small host-side metadata, error paths) is allowlisted inline
  with ``# jit-ok: <reason>`` — the reason doubles as documentation of
  WHY it is cold.
- JIT002: array constructors (``jnp.zeros/ones/full/empty/arange``,
  and the ``np`` equivalents feeding device puts) whose shape argument
  is not a compile-time constant — unbucketed Python-varying shapes
  recompile per distinct value; route them through a bucketing helper
  (``_bucket``/``_nb_bucket``/``_suffix_bucket``) first.

The pass is name-based, not type-based — that is the point of the
allowlist: every ``np.asarray`` on a hot path is either a sync hazard
or deliberately cold, and the code must say which.
"""
import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from skypilot_tpu.analysis.findings import Finding

_OK_RE = re.compile(r'#\s*jit-ok\b')

PASS_HOST_SYNC = 'JIT001'
PASS_VARYING_SHAPE = 'JIT002'

# Hot roots per repo-relative path: class -> dispatch-path methods.
# Reachability closes over self.<method>() calls within the class.
HOT_ROOTS: Dict[str, Dict[str, List[str]]] = {
    'skypilot_tpu/infer/engine.py': {
        'InferenceEngine': [
            '_step', '_decode_step', '_spec_step', '_chunk_round',
            '_dispatch_decode', '_maybe_dispatch_ahead',
            '_consume_window', '_start_batch',
        ],
    },
}

_NP_MODULES = {'np', 'numpy'}
_CONSTRUCTORS = {'zeros', 'ones', 'full', 'empty', 'arange'}
_SYNC_METHODS = {'item', 'block_until_ready'}


def _callee_self_method(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == 'self':
        return f.attr
    return None


def _module_attr(node: ast.AST) -> Optional[str]:
    """'np.asarray' / 'jax.device_get' -> dotted name, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name):
        return f'{node.value.id}.{node.attr}'
    return None


def _is_constant_shape(node: ast.AST) -> bool:
    """Shape args that cannot vary per call: int/None constants,
    tuples/lists of them, and unary minus on a constant."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_constant_shape(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _is_constant_shape(node.operand)
    return False


class _HotVisitor(ast.NodeVisitor):

    def __init__(self, path: str, lines: List[str], method: str,
                 findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.method = method
        self.findings = findings

    def _allowlisted(self, lineno: int) -> bool:
        return (lineno <= len(self.lines)
                and _OK_RE.search(self.lines[lineno - 1]) is not None)

    def _add(self, lineno: int, pass_id: str, msg: str) -> None:
        if not self._allowlisted(lineno):
            self.findings.append(Finding(self.path, lineno, pass_id,
                                         msg))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        dotted = _module_attr(f)
        where = f"jit-reachable '{self.method}'"
        if dotted in ('jax.device_get',) or (
                dotted is not None and
                dotted.split('.', 1)[0] in _NP_MODULES and
                dotted.split('.', 1)[1] in ('asarray', 'array')):
            self._add(node.lineno, PASS_HOST_SYNC,
                      f'host sync {dotted}(...) inside {where} '
                      '(device->host copy blocks the dispatch path; '
                      "mark known-cold sites '# jit-ok: <reason>')")
        elif isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS \
                and not isinstance(f.value, ast.Name):
            # obj.item() / obj.block_until_ready() on a non-module
            # value (module functions handled above).
            self._add(node.lineno, PASS_HOST_SYNC,
                      f'host sync .{f.attr}() inside {where}')
        elif isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS \
                and isinstance(f.value, ast.Name) and \
                f.value.id not in _NP_MODULES and f.value.id != 'jax':
            self._add(node.lineno, PASS_HOST_SYNC,
                      f'host sync .{f.attr}() inside {where}')
        if dotted is not None:
            mod, attr = dotted.split('.', 1)
            if (mod in _NP_MODULES or mod == 'jnp') and \
                    attr in _CONSTRUCTORS and node.args:
                if not _is_constant_shape(node.args[0]):
                    self._add(
                        node.lineno, PASS_VARYING_SHAPE,
                        f'{dotted}(...) with a Python-varying shape '
                        f'inside {where} (recompiles per distinct '
                        'value; bucket the size first)')
        self.generic_visit(node)


def _reachable(cls: ast.ClassDef, roots: Iterable[str]) -> Set[str]:
    methods = {m.name: m for m in cls.body
               if isinstance(m, (ast.FunctionDef,
                                 ast.AsyncFunctionDef))}
    edges: Dict[str, Set[str]] = {}
    for name, meth in methods.items():
        callees = set()
        for node in ast.walk(meth):
            if isinstance(node, ast.Call):
                callee = _callee_self_method(node)
                if callee in methods:
                    callees.add(callee)
        edges[name] = callees
    seen: Set[str] = set()
    stack = [r for r in roots if r in methods]
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(edges.get(cur, ()) - seen)
    return seen


def check_file(path: str, text: str,
               roots: Optional[Dict[str, List[str]]] = None
               ) -> List[Finding]:
    """``roots``: class -> root methods; defaults to HOT_ROOTS[path]."""
    if roots is None:
        roots = HOT_ROOTS.get(path)
    if not roots:
        return []
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []
    lines = text.splitlines()
    findings: List[Finding] = []
    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef) and n.name in roots]:
        hot = _reachable(cls, roots[cls.name])
        for meth in cls.body:
            if isinstance(meth, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    meth.name in hot:
                visitor = _HotVisitor(path, lines, meth.name, findings)
                for stmt in meth.body:
                    visitor.visit(stmt)
    return findings
