"""LOCK pass: lock-guarded fields may only be mutated under their lock.

Conventions (all line comments, so they double as documentation at the
declaration site):

- ``self._foo = ... # guarded-by: _lock`` on an assignment (anywhere in
  the class, conventionally ``__init__``) declares ``_foo`` guarded by
  ``self._lock``.  Every later ``self._foo = / += / [k] = / del``
  outside a ``with self._lock:`` block is LOCK001.
- ``def _helper(self): # locked: _lock`` (trailing the ``def`` line or
  on the line above) asserts the CALLER holds ``_lock`` — the helper's
  body is checked as if the lock were held.  This is how "caller holds
  the lock" tribal knowledge becomes machine-checked: annotating a
  helper that some caller invokes bare is a bug the runtime lock-order
  sanitizer and review must catch, so annotate deliberately.
- ``# lock-ok: <reason>`` on a mutating line suppresses LOCK001 for an
  intentional benign race (single-writer fields read lock-free).

Checked mutations are assignments (plain/aug/ann), subscript stores and
``del`` whose target roots at ``self.<field>``.  Mutating *method*
calls (``.append``, ``.pop``, ``.clear`` ...) are NOT tracked — too
alias-prone for an AST pass — so guarded containers still rely on
review for those; the pass catches the rebinding and item-store
patterns that dominate this codebase.

``__init__`` is exempt end-to-end (construction happens-before
publication).  Nested ``def``s inherit the lexical lock context of
their definition site (optimistic: closures created under the lock are
overwhelmingly called under it here).

LOCK002 flags acquiring a lock that is already held — ``with
self._lock:`` nested inside another (lexically, or inside a helper
annotated ``# locked:``) deadlocks, because these are plain
non-reentrant ``threading.Lock``s.
"""
import ast
import re
from typing import Dict, List, Optional, Set

from skypilot_tpu.analysis.findings import Finding

_GUARDED_RE = re.compile(r'#\s*guarded-by:\s*([A-Za-z_]\w*)')
_LOCKED_RE = re.compile(r'#\s*locked:\s*([A-Za-z_]\w*)')
_OK_RE = re.compile(r'#\s*lock-ok\b')

PASS_MUTATION = 'LOCK001'
PASS_REENTRY = 'LOCK002'


def _self_field(node: ast.AST) -> Optional[str]:
    """Root field name for a mutation target: self.X, self.X[...],
    self.X.attr, self.X[...][...] ... -> 'X'; anything else -> None."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        parent = node.value
        if isinstance(node, ast.Attribute) and \
                isinstance(parent, ast.Name) and parent.id == 'self':
            return node.attr
        node = parent
    return None


def _with_lock_names(node: ast.With, lock_names: Set[str]) -> Set[str]:
    """Lock attrs acquired by a ``with`` statement: items of the form
    ``self.<lock>`` (optionally aliased with ``as``)."""
    out = set()
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == 'self' and expr.attr in lock_names:
            out.add(expr.attr)
    return out


class _MethodChecker(ast.NodeVisitor):

    def __init__(self, path: str, lines: List[str],
                 guarded: Dict[str, str], lock_names: Set[str],
                 held: Set[str], findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.guarded = guarded
        self.lock_names = lock_names
        self.held = set(held)
        self.findings = findings

    # ------------------------------------------------------ lock scope

    def visit_With(self, node: ast.With) -> None:
        acquired = _with_lock_names(node, self.lock_names)
        for name in acquired & self.held:
            self.findings.append(Finding(
                self.path, node.lineno, PASS_REENTRY,
                f"nested 'with self.{name}' while '{name}' is already "
                "held - threading.Lock is not reentrant"))
        self.held |= acquired
        self.generic_visit(node)
        self.held -= acquired

    # Nested defs inherit the current lock context (see module doc).
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # ------------------------------------------------------- mutations

    def _check_target(self, target: ast.AST, lineno: int) -> None:
        field = _self_field(target)
        if field is None or field not in self.guarded:
            return
        lock = self.guarded[field]
        if lock in self.held:
            return
        line = self.lines[lineno - 1] if lineno <= len(self.lines) else ''
        if _OK_RE.search(line):
            return
        self.findings.append(Finding(
            self.path, lineno, PASS_MUTATION,
            f"field '{field}' (guarded by '{lock}') mutated outside "
            f"'with self.{lock}'"))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                for el in t.elts:
                    self._check_target(el, node.lineno)
            else:
                self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)


def _line_annotation(lines: List[str], lineno: int,
                     regex: re.Pattern) -> Optional[str]:
    """Match ``regex`` on ``lineno`` or the line directly above."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = regex.search(lines[ln - 1])
            if m:
                return m.group(1)
    return None


def _collect_guarded(cls: ast.ClassDef,
                     lines: List[str]) -> Dict[str, str]:
    """field -> lock name, from ``# guarded-by:`` annotated
    ``self.X = ...`` assignments anywhere in the class body."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        lock = None
        if node.lineno <= len(lines):
            m = _GUARDED_RE.search(lines[node.lineno - 1])
            lock = m.group(1) if m else None
        if lock is None:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == 'self':
                guarded[t.attr] = lock
    return guarded


def check_file(path: str, text: str) -> List[Finding]:
    findings: List[Finding] = []
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, PASS_MUTATION,
                        f'unparseable file: {e.msg}')]
    lines = text.splitlines()
    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)]:
        guarded = _collect_guarded(cls, lines)
        if not guarded:
            continue
        lock_names = set(guarded.values())
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name == '__init__':
                continue
            held: Set[str] = set()
            locked = _line_annotation(lines, meth.lineno, _LOCKED_RE)
            if locked is not None:
                held.add(locked)
            checker = _MethodChecker(path, lines, guarded, lock_names,
                                     held, findings)
            for stmt in meth.body:
                checker.visit(stmt)
    return findings
