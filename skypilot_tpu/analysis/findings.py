"""Finding type + baseline handling for skycheck.

A finding renders as ``path:line: [PASS-ID] message``.  The baseline
file (``skycheck_baseline.txt``) stores rendered findings verbatim, but
comparison keys on ``(path, pass_id, message)`` — NOT the line number —
so an unrelated edit that shifts a pinned finding by a few lines does
not churn the baseline or break CI.  Two identical findings in one file
(same message, different lines) are counted: the baseline absorbs as
many as it pins, and any excess is new.
"""
import collections
import dataclasses
import re
from typing import Dict, Iterable, List, Tuple

_RENDERED = re.compile(r'^(?P<path>.+?):(?P<line>\d+): '
                       r'\[(?P<pass_id>[A-Z]+\d+)\] (?P<message>.*)$')

Key = Tuple[str, str, str]          # (path, pass_id, message)


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    pass_id: str
    message: str

    def render(self) -> str:
        return f'{self.path}:{self.line}: [{self.pass_id}] {self.message}'

    @property
    def key(self) -> Key:
        return (self.path, self.pass_id, self.message)


def load_baseline(path: str) -> Dict[Key, int]:
    """Parse a baseline file into a key -> pinned-count map.  Blank
    lines and ``#`` comments are skipped; a malformed line is an error
    (a silently ignored pin would un-pin a finding)."""
    counts: Dict[Key, int] = collections.Counter()
    try:
        with open(path, encoding='utf-8') as f:
            lines = f.read().splitlines()
    except FileNotFoundError:
        return {}
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line or line.startswith('#'):
            continue
        m = _RENDERED.match(line)
        if m is None:
            raise ValueError(
                f'{path}:{i}: unparseable baseline line: {line!r}')
        counts[(m.group('path'), m.group('pass_id'),
                m.group('message'))] += 1
    return dict(counts)


def new_findings(findings: Iterable[Finding],
                 baseline: Dict[Key, int]
                 ) -> Tuple[List[Finding], int]:
    """Split findings against the baseline.  Returns
    ``(new, fixed_count)``: findings beyond their pinned count (sorted),
    and how many pinned findings no longer occur (candidates for
    shrinking the baseline)."""
    seen: Dict[Key, int] = collections.Counter()
    new: List[Finding] = []
    for f in sorted(findings):
        seen[f.key] += 1
        if seen[f.key] > baseline.get(f.key, 0):
            new.append(f)
    fixed = sum(max(0, pinned - seen.get(key, 0))
                for key, pinned in baseline.items())
    return new, fixed
