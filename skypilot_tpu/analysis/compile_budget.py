"""COMPILE pass: provable worst-case XLA compile counts per jit root.

Every jitted dispatch root in the engine keys its compile cache on the
SHAPES of its array arguments and the VALUES of its static arguments.
The whole recompilation-storm discipline (TPU-pod playbook: tracing is
a first-order cost) rests on one convention: every dynamic dimension
reaching a root must come out of a finite bucketing helper —

- ``_bucket`` / ``_suffix_bucket``: the prefill ladder
  (``cfg.prefill_buckets``),
- ``_nb_bucket``: the pow2 table-width ladder (capped at
  ``_max_blocks``),
- ``_select_window``: the adaptive decode window (two variants).

This pass makes the convention checkable.  It discovers the roots from
the ``self._NAME = jax.jit(...)`` builds, walks every ``self._NAME(...)``
call site, and resolves each argument's shape dims (value, for static
argnums) back to bucket symbols through locals, parameters and caller
argument expressions.  A dimension that bottoms out anywhere else is
**COMPILE001**: an unbounded shape dimension — one compile per distinct
runtime value, the storm the ladder exists to prevent.

For dims the dataflow cannot see through (loop targets over group
dicts), an inline annotation asserts the symbol::

    for (bucket, aid), group in groups.items():  # compile-shape: bucket=prefill_buckets

The static worst case per root is the sum over call sites of the
product of each site's symbol cardinalities (sites are summed, not
deduped — an upper bound stays an upper bound).  ``root_bounds``
evaluates it for an explicit ``model``; ``runtime_model`` derives the
model from a live engine's config, which is what the env-gated runtime
sanitizer (``SKYTPU_COMPILE_SANITIZER`` in ``analysis.sanitizers``)
asserts measured compile counts against at quiesce.
"""
import ast
import math
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import dataflow
from skypilot_tpu.analysis.findings import Finding

PASS_UNBOUNDED = 'COMPILE001'

ENGINE_FILE = 'skypilot_tpu/infer/engine.py'

# Bucketing helpers -> the symbol naming their output lattice.
SYMBOL_FUNCS = {
    '_bucket': 'prefill_buckets',
    '_nb_bucket': 'nb_buckets',
    '_suffix_bucket': 'suffix_buckets',
    '_select_window': 'decode_windows',
}

# A boolean static argument computed at the call site (want_plp =
# any(...)): both variants compile.
BOOL_SYMBOL = 'static_bool'

# The inline pow2-floor ladder over registered-prefix lengths
# (_start_prefixed_group's b_ loop): only assertable by annotation.
PREFIX_SYMBOL = 'prefix_pow2'

SYMBOLS = tuple(sorted(set(SYMBOL_FUNCS.values()))) + (
    BOOL_SYMBOL, PREFIX_SYMBOL)

_ANNOT_RE = re.compile(
    r'#\s*compile-shape:\s*(\w+)\s*=\s*(\w+)')

# Array constructors whose first argument is the shape.
_SHAPE_CTORS = frozenset({
    'np.zeros', 'np.ones', 'np.full', 'np.empty',
    'jnp.zeros', 'jnp.ones', 'jnp.full', 'jnp.empty',
})
# Calls that pass their first argument's array shape through.
_PASSTHROUGH = frozenset({
    'np.asarray', 'jnp.asarray', 'np.ascontiguousarray',
    'jax.device_put',
})
# Fixed-shape producers (PRNG keys).
_FIXED_CALLS = frozenset({
    'jax.random.PRNGKey', 'jax.random.split', 'jax.random.fold_in',
})


class RootSpec:
    def __init__(self, name: str, line: int,
                 static_argnums: Tuple[int, ...]) -> None:
        self.name = name
        self.line = line
        self.static_argnums = static_argnums


def discover_roots(text: str) -> List[RootSpec]:
    """``self._NAME = jax.jit(fn, ...)`` assignments, with their
    static_argnums."""
    tree = ast.parse(text)
    roots: List[RootSpec] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and
                len(node.targets) == 1 and
                isinstance(node.targets[0], ast.Attribute) and
                isinstance(node.targets[0].value, ast.Name) and
                node.targets[0].value.id == 'self' and
                isinstance(node.value, ast.Call) and
                dataflow.dotted_name(node.value.func) == 'jax.jit'):
            continue
        static: Tuple[int, ...] = ()
        for kw in node.value.keywords:
            if kw.arg == 'static_argnums' and \
                    isinstance(kw.value, ast.Tuple):
                static = tuple(
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant) and
                    isinstance(e.value, int))
        roots.append(RootSpec(node.targets[0].attr, node.lineno,
                              static))
    return roots


def _annotations(index: dataflow.ModuleIndex,
                 fn: dataflow.FunctionInfo) -> Dict[str, str]:
    """``# compile-shape: NAME=SYMBOL`` lines inside the function."""
    start = fn.node.lineno
    end = getattr(fn.node, 'end_lineno', start)
    out: Dict[str, str] = {}
    for ln in range(start, min(end, len(index.lines)) + 1):
        m = _ANNOT_RE.search(index.lines[ln - 1])
        if m:
            out[m.group(1)] = m.group(2)
    return out


class _Resolver:
    """Symbol resolution for one call site's arguments: dims and
    static values back to bucket symbols, interprocedurally."""

    def __init__(self, index: dataflow.ModuleIndex) -> None:
        self.index = index
        self.symbols: Set[str] = set()
        self.unresolved: List[Tuple[int, str]] = []

    # -- dim/value position ----------------------------------------

    def dim(self, fn: dataflow.FunctionInfo, expr: ast.expr,
            depth: int = 5,
            seen: Optional[Set[Tuple[str, str]]] = None) -> None:
        seen = seen if seen is not None else set()
        if isinstance(expr, dataflow._Opaque):
            return self._miss(0, 'tuple-unpacked value')
        if isinstance(expr, ast.Constant):
            return
        if isinstance(expr, ast.Attribute):
            return          # self.cfg.* / self._max_blocks: fixed
        if isinstance(expr, (ast.UnaryOp,)):
            return self.dim(fn, expr.operand, depth, seen)
        if isinstance(expr, ast.BinOp):
            self.dim(fn, expr.left, depth, seen)
            self.dim(fn, expr.right, depth, seen)
            return
        if isinstance(expr, ast.IfExp):
            self.dim(fn, expr.body, depth, seen)
            self.dim(fn, expr.orelse, depth, seen)
            return
        if isinstance(expr, ast.Compare):
            self.symbols.add(BOOL_SYMBOL)
            return
        if isinstance(expr, ast.Call):
            name = dataflow.dotted_name(expr.func)
            if name is not None and name.startswith('self.'):
                attr = name[5:]
                if attr in SYMBOL_FUNCS:
                    self.symbols.add(SYMBOL_FUNCS[attr])
                    return
            if name in ('min', 'max', 'int', 'abs', 'round'):
                for a in expr.args:
                    self.dim(fn, a, depth, seen)
                return
            if name in ('any', 'all', 'bool'):
                self.symbols.add(BOOL_SYMBOL)
                return
            return self._miss(expr.lineno,
                              f'call {name or "<expr>"}(...)')
        if isinstance(expr, ast.Name):
            return self._via_name(fn, expr, depth, seen, self.dim)
        self._miss(getattr(expr, 'lineno', 0),
                   f'{type(expr).__name__} expression')

    # -- array position --------------------------------------------

    def array(self, fn: dataflow.FunctionInfo, expr: ast.expr,
              depth: int = 5,
              seen: Optional[Set[Tuple[str, str]]] = None) -> None:
        seen = seen if seen is not None else set()
        if isinstance(expr, dataflow._Opaque):
            return self._miss(0, 'tuple-unpacked array')
        if isinstance(expr, (ast.Constant, ast.Attribute)):
            return          # self.cache / self.params: fixed shapes
        if isinstance(expr, ast.BinOp):
            self.array(fn, expr.left, depth, seen)
            self.array(fn, expr.right, depth, seen)
            return
        if isinstance(expr, ast.Subscript):
            return self.array(fn, expr.value, depth, seen)
        if isinstance(expr, ast.Call):
            name = dataflow.dotted_name(expr.func)
            if name in _SHAPE_CTORS and expr.args:
                shape = expr.args[0]
                elts = shape.elts if isinstance(
                    shape, (ast.Tuple, ast.List)) else [shape]
                for e in elts:
                    self.dim(fn, e, depth, seen)
                return
            if name in _PASSTHROUGH and expr.args:
                return self.array(fn, expr.args[0], depth, seen)
            if name in _FIXED_CALLS:
                return
            if name == 'range' and expr.args:
                for a in expr.args:
                    self.dim(fn, a, depth, seen)
                return
            if name is not None and name.startswith('self.'):
                attr = name[5:]
                if attr == '_lane_tables' and len(expr.args) == 2:
                    self.array(fn, expr.args[0], depth, seen)
                    self.dim(fn, expr.args[1], depth, seen)
                    return
                helper = self.index.find(attr)
                if helper is not None and depth > 0:
                    # A shape-producing helper (e.g. _decode_tables):
                    # the returned array's dims are whatever the
                    # helper's own return expressions resolve to.
                    key = (helper.qualname, '<return>')
                    if key in seen:
                        return
                    seen.add(key)
                    for node in dataflow._walk_no_nested(helper.node):
                        if isinstance(node, ast.Return) and \
                                node.value is not None:
                            self.array(helper, node.value,
                                       depth - 1, seen)
                    return
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr in ('astype', 'copy', 'reshape'):
                return self.array(fn, expr.func.value, depth, seen)
            if name in ('int', 'float') and expr.args:
                return      # python scalar: shape-() weak-typed arg
            if name == 'init_cache':
                # (model_config, width, bucket, dtype): dims are the
                # two middle arguments.
                for a in expr.args[1:3]:
                    self.dim(fn, a, depth, seen)
                return
            return self._miss(expr.lineno,
                              f'call {name or "<expr>"}(...)')
        if isinstance(expr, ast.Name):
            if self._prng_unpack(fn, expr.id):
                return
            return self._via_name(fn, expr, depth, seen, self.array)
        self._miss(getattr(expr, 'lineno', 0),
                   f'{type(expr).__name__} expression')

    # -- shared name resolution ------------------------------------

    def _via_name(self, fn, expr, depth, seen, recurse) -> None:
        annot = _annotations(self.index, fn).get(expr.id)
        if annot is not None:
            if annot in SYMBOLS:
                self.symbols.add(annot)
            elif annot != 'const':
                self._miss(expr.lineno,
                           f'unknown compile-shape symbol {annot!r}')
            return
        key = (fn.qualname, expr.id)
        if key in seen or depth <= 0:
            return
        seen.add(key)
        defs = dataflow._defs_cache(self.index, fn).get(expr.id)
        if defs:
            for d in defs:
                recurse(fn, d, depth - 1, seen)
            return
        params = fn.params
        if expr.id in params:
            sites = self.index.call_sites.get(
                fn.qualname.rsplit('.', 1)[-1], [])
            resolved = False
            for caller, call in sites:
                arg = dataflow._arg_for_param(fn, call, expr.id)
                if arg is not None:
                    recurse(caller, arg, depth - 1, seen)
                    resolved = True
            if resolved:
                return
            default = fn.defaults.get(expr.id)
            if default is not None:
                return recurse(fn, default, depth - 1, seen)
        self._miss(expr.lineno, f"name '{expr.id}'")

    def _prng_unpack(self, fn: dataflow.FunctionInfo,
                     name: str) -> bool:
        """``self._rng, key = jax.random.split(...)``: fixed-shape PRNG
        keys bound by tuple unpack (which local_defs marks opaque)."""
        for node in dataflow._walk_no_nested(fn.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    dataflow.dotted_name(node.value.func) in \
                    _FIXED_CALLS:
                for tgt in node.targets:
                    for e in getattr(tgt, 'elts', [tgt]):
                        if isinstance(e, ast.Name) and e.id == name:
                            return True
        return False

    def _miss(self, line: int, what: str) -> None:
        self.unresolved.append((line, what))


def root_profiles(text: str, path: str = ENGINE_FILE
                  ) -> Tuple[Dict[str, List[Tuple[str, ...]]],
                             List[Finding]]:
    """Per root: one sorted symbol tuple per call site, plus COMPILE001
    findings for every dimension that resolved to nothing bounded."""
    index = dataflow.ModuleIndex(path, text)
    roots = discover_roots(text)
    profiles: Dict[str, List[Tuple[str, ...]]] = {}
    findings: List[Finding] = []
    emitted: Set[Tuple[int, str]] = set()
    for root in roots:
        sites = index.call_sites.get(root.name, [])
        profiles[root.name] = []
        for caller, call in sites:
            res = _Resolver(index)
            for i, arg in enumerate(call.args):
                if i in root.static_argnums:
                    res.dim(caller, arg)
                else:
                    res.array(caller, arg)
            profiles[root.name].append(tuple(sorted(res.symbols)))
            for line, what in res.unresolved:
                key = (line or call.lineno, what)
                if key in emitted:
                    continue
                emitted.add(key)
                findings.append(Finding(
                    path, line or call.lineno, PASS_UNBOUNDED,
                    f'{caller.qualname} -> {root.name}: shape/static '
                    f'dimension from {what} is not provably bucketed '
                    '(one XLA compile per distinct runtime value); '
                    'route it through a bucketing helper or assert it '
                    'with a # compile-shape: annotation'))
    findings.sort(key=lambda f: (f.line, f.message))
    return profiles, findings


def root_bounds(text: str, model: Dict[str, int],
                path: str = ENGINE_FILE) -> Dict[str, int]:
    """Provable worst-case compile count per root under ``model``
    (symbol -> cardinality): sum over call sites of the product of the
    site's symbol cardinalities."""
    profiles, _ = root_profiles(text, path)
    out: Dict[str, int] = {}
    for name, sites in profiles.items():
        total = 0
        for syms in sites:
            site = 1
            for s in syms:
                site *= model.get(s, 1)
            total += site
        out[name] = total
    return out


def nb_ladder_size(max_blocks: int) -> int:
    """Cardinality of ``_nb_bucket``'s output lattice: pow2 values
    1, 2, 4, ... capped at max_blocks (the cap itself included when it
    is not a power of two)."""
    if max_blocks <= 1:
        return 1
    n = math.floor(math.log2(max_blocks - 1)) + 1 \
        if max_blocks > 1 else 0
    pow2s = n + 1                      # 1, 2, ..., 2**n
    if 2 ** n >= max_blocks and 2 ** (n - 1) < max_blocks and \
            2 ** n != max_blocks:
        # The while-loop cap replaces the overshooting pow2 with
        # max_blocks itself — same count, different value.
        return pow2s
    return pow2s


def runtime_model(engine) -> Dict[str, int]:
    """The symbol cardinalities of a LIVE engine's config — what the
    runtime compile sanitizer asserts measured counts against."""
    cfg = engine.cfg
    buckets = len(tuple(cfg.prefill_buckets))
    max_blocks = int(getattr(engine, '_max_blocks', 1) or 1)
    max_len = int(getattr(cfg, 'max_cache_len', 2048) or 2048)
    return {
        'prefill_buckets': buckets,
        'suffix_buckets': buckets,
        'nb_buckets': nb_ladder_size(max_blocks),
        'decode_windows': 2 if getattr(cfg, 'adaptive_decode_window',
                                       False) else 1,
        BOOL_SYMBOL: 2,
        # pow2-floor of a registered-prefix length < max_cache_len.
        PREFIX_SYMBOL: max(1, math.floor(math.log2(max_len)) + 1),
    }


def check_engine_budget(engine) -> Dict[str, Tuple[int, int]]:
    """measured-vs-bound per jit root of a live engine; the runtime
    sanitizer raises when measured exceeds the provable bound."""
    import inspect
    mod = inspect.getmodule(type(engine))
    text = inspect.getsource(mod)
    bounds = root_bounds(text, runtime_model(engine))
    out: Dict[str, Tuple[int, int]] = {}
    for name, bound in bounds.items():
        fn = getattr(engine, name, None)
        size = getattr(fn, '_cache_size', None)
        if fn is None or size is None:
            continue
        out[name] = (int(size()), bound)
    return out


def check_file(path: str, text: str) -> List[Finding]:
    if path != ENGINE_FILE:
        return []
    try:
        _, findings = root_profiles(text, path)
    except SyntaxError:
        return []
    return findings
