"""skycheck: codebase-specific static analysis + runtime sanitizers.

Static passes (driven by ``scripts/skycheck.py``):

- ``lock_discipline`` (LOCK001/LOCK002): fields annotated
  ``# guarded-by: <lock>`` may only be mutated inside
  ``with self.<lock>:``; nested acquisition of the same
  non-reentrant lock is a deadlock.
- ``jit_boundary`` (JIT001/JIT002): host-device syncs and
  Python-varying shapes inside functions reachable from the jitted
  decode/prefill dispatch paths.
- ``layering`` (LAYER001): the import DAG — ``infer`` never imports
  ``serve``; ``serve`` never imports ``infer.engine`` internals;
  ``ops`` imports neither.
- ``determinism`` (DET001/DET002): bare wall clocks and unseeded RNG
  in the serve plane and the fault/chaos tooling, outside the
  injected clock/rng seams.

Runtime sanitizers (``sanitizers``; env-gated, zero overhead off):
a lock-order checker over the engine/LB/breaker locks and a
block-leak checker asserting paged-pool refcount conservation.

Findings print as ``path:line: [PASS-ID] message``; a checked-in
``skycheck_baseline.txt`` pins pre-existing findings so CI fails only
on regressions (comparison ignores line numbers, so unrelated edits
don't churn the baseline).
"""
from skypilot_tpu.analysis.findings import Finding, load_baseline, new_findings
from skypilot_tpu.analysis.walker import iter_py_files

__all__ = ['Finding', 'load_baseline', 'new_findings', 'iter_py_files']
