"""skycheck: codebase-specific static analysis + runtime sanitizers.

Static passes (driven by ``scripts/skycheck.py``):

- ``lock_discipline`` (LOCK001/LOCK002): fields annotated
  ``# guarded-by: <lock>`` may only be mutated inside
  ``with self.<lock>:``; nested acquisition of the same
  non-reentrant lock is a deadlock.
- ``jit_boundary`` (JIT001/JIT002): host-device syncs and
  Python-varying shapes inside functions reachable from the jitted
  decode/prefill dispatch paths.
- ``layering`` (LAYER001): the import DAG — ``infer`` never imports
  ``serve``; ``serve`` never imports ``infer.engine`` internals;
  ``ops`` imports neither.
- ``determinism`` (DET001/DET002): bare wall clocks and unseeded RNG
  in the serve plane and the fault/chaos tooling, outside the
  injected clock/rng seams.
- ``wire_contract`` (WIRE001-003, whole-tree): the JSON wire contract
  between planes — every key a registered consumer reads off an HTTP
  surface is produced unconditionally; orphans and type conflicts.
- ``block_lifecycle`` (BLOCK001/BLOCK002): path-sensitive proofs that
  every allocated block-id list reaches exactly one release sink on
  every path, including jit exception edges.
- ``compile_budget`` (COMPILE001): every shape/static dimension
  reaching a ``jax.jit`` root resolves to a finite bucket symbol, with
  provable per-root compile-count bounds.
- ``shard_contract`` (SHARD001-004, whole-tree): the sharding contract
  of the mesh-using modules — axis names against the
  ``parallel/mesh.py`` vocabulary, registry-declared buffers must be
  sharded before reaching jit roots, host transfers on sharded values,
  and divisibility guards for sharded dimensions.

Runtime sanitizers (``sanitizers``; env-gated, zero overhead off):
a lock-order checker over the engine/LB/breaker locks, a block-leak
checker asserting paged-pool refcount conservation, a compile-budget
checker pinning each jit root's XLA cache size to its proven bound,
and a shard-layout checker asserting a mesh-bearing engine's committed
params/cache layouts match the declared registry.

Findings print as ``path:line: [PASS-ID] message``; a checked-in
``skycheck_baseline.txt`` pins pre-existing findings so CI fails only
on regressions (comparison ignores line numbers, so unrelated edits
don't churn the baseline).
"""
from skypilot_tpu.analysis.findings import Finding, load_baseline, new_findings
from skypilot_tpu.analysis.walker import iter_py_files

__all__ = ['Finding', 'load_baseline', 'new_findings', 'iter_py_files']
