"""DET pass: no bare clocks or ambient randomness in replayable code.

The fault-tolerance story (PR 3/5) depends on deterministic replay:
``FaultPlan`` schedules, chaos seeds and failover traces only reproduce
if the serve plane and the fault machinery draw time and randomness
through injected seams.  This pass bans, inside ``skypilot_tpu/serve/``
plus ``infer/faults.py`` and ``infer/chaos.py``:

- DET001: bare ``time.time()`` / ``time.monotonic()`` calls.  Route
  through an injected ``now``/``clock`` callable (see
  ``CircuitBreaker(now=...)``) or a ``_now()`` test hook.
- DET002: ambient ``random.*`` module calls and the numpy equivalents
  (``np.random.<fn>`` and argument-less ``np.random.default_rng()``).
  Seeded generator construction — ``random.Random(seed)``,
  ``np.random.default_rng(seed)`` — is allowed: that IS the seam.

``# det-ok: <reason>`` on the call line allowlists a deliberate bare
clock (e.g. a wall-clock test hook that tests monkeypatch, or a
harness-side wait loop that never feeds replayed state).
"""
import ast
import re
from typing import List, Optional, Sequence

from skypilot_tpu.analysis.findings import Finding

_OK_RE = re.compile(r'#\s*det-ok\b')

PASS_CLOCK = 'DET001'
PASS_RANDOM = 'DET002'

# Repo-relative prefixes/paths where determinism is load-bearing.
SCOPE: Sequence[str] = (
    'skypilot_tpu/serve/',
    'skypilot_tpu/infer/faults.py',
    'skypilot_tpu/infer/chaos.py',
)

_CLOCK_FNS = {'time', 'monotonic', 'monotonic_ns', 'time_ns',
              'perf_counter', 'perf_counter_ns'}
# random-module functions that draw from the ambient global generator.
_AMBIENT_RANDOM = {
    'random', 'randint', 'randrange', 'choice', 'choices', 'shuffle',
    'sample', 'uniform', 'gauss', 'normalvariate', 'expovariate',
    'betavariate', 'gammavariate', 'triangular', 'seed', 'getrandbits',
}


def in_scope(path: str, scope: Optional[Sequence[str]] = None) -> bool:
    scope = SCOPE if scope is None else scope
    return any(path == s or (s.endswith('/') and path.startswith(s))
               for s in scope)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


class _Visitor(ast.NodeVisitor):

    def __init__(self, path: str, lines: List[str],
                 findings: List[Finding]):
        self.path = path
        self.lines = lines
        self.findings = findings

    def _allowlisted(self, lineno: int) -> bool:
        return (lineno <= len(self.lines)
                and _OK_RE.search(self.lines[lineno - 1]) is not None)

    def _add(self, lineno: int, pass_id: str, msg: str) -> None:
        if not self._allowlisted(lineno):
            self.findings.append(Finding(self.path, lineno, pass_id,
                                         msg))

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        if dotted is not None:
            parts = dotted.split('.')
            if len(parts) == 2 and parts[0] == 'time' and \
                    parts[1] in _CLOCK_FNS:
                self._add(node.lineno, PASS_CLOCK,
                          f'bare clock {dotted}() - inject a '
                          "now/clock callable (or mark the seam "
                          "'# det-ok: <reason>')")
            elif len(parts) == 2 and parts[0] == 'random' and \
                    parts[1] in _AMBIENT_RANDOM:
                self._add(node.lineno, PASS_RANDOM,
                          f'ambient randomness {dotted}() - use a '
                          'seeded random.Random instance')
            elif len(parts) == 3 and parts[0] in ('np', 'numpy') and \
                    parts[1] == 'random':
                if parts[2] == 'default_rng':
                    if not node.args and not node.keywords:
                        self._add(node.lineno, PASS_RANDOM,
                                  f'{dotted}() without a seed - pass '
                                  'an explicit seed')
                else:
                    self._add(node.lineno, PASS_RANDOM,
                              f'ambient randomness {dotted}() - use a '
                              'seeded np.random.default_rng(seed)')
        self.generic_visit(node)


def check_file(path: str, text: str,
               scope: Optional[Sequence[str]] = None) -> List[Finding]:
    if not in_scope(path, scope):
        return []
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []
    findings: List[Finding] = []
    _Visitor(path, text.splitlines(), findings).visit(tree)
    return findings
