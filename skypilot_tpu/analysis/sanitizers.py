"""Opt-in runtime sanitizers: lock-order checking, block-leak
detection, and compile-budget enforcement.

All are env-gated and cost nothing when off:

- ``SKYTPU_LOCK_SANITIZER=1`` — ``instrument_lock(lock, name)`` wraps a
  ``threading.Lock`` so every acquisition records (per-thread) what was
  already held, feeding a global lock-order graph.  Acquiring A while
  holding B after some thread ever acquired B while holding A raises
  ``LockOrderError`` — the ABBA inversion is caught even when the
  timing never actually deadlocks.  Re-acquiring a lock the current
  thread already holds raises immediately (non-reentrant
  ``threading.Lock`` would block forever), *before* touching the real
  lock.  When the gate is off ``instrument_lock`` returns the raw lock
  unchanged — zero overhead, not merely low.
- ``SKYTPU_BLOCK_SANITIZER=1`` — ``check_block_conservation(engine)``
  verifies the paged pool's refcount conservation law at a quiesce
  point: for every block, the allocator refcount equals the number of
  slot-table entries + radix-tree nodes + registered-prefix entries
  holding it, and the free list is exactly the zero-refcount blocks.
  Violations raise ``BlockLeakError`` naming the first few offending
  blocks.  When the host KV tier is armed, its byte-ledger audit runs
  under the same lock: ledger/entry drift or a budget overrun is a
  leak across the tier boundary and fails the same check.  The serving
  loop calls ``maybe_check_block_conservation`` on idle iterations;
  chaos_smoke and the fault tests call the checker directly after
  drain.
- ``SKYTPU_COMPILE_SANITIZER=1`` — ``check_compile_budget(engine)``
  asserts, per jit root, that the number of XLA compilations the root
  has actually accumulated (``fn._cache_size()``) is within the
  PROVABLE worst case the static COMPILE pass derives from the
  engine's source and this engine's config
  (``analysis.compile_budget``).  A measured count above the bound
  means a shape dimension escaped the bucketing ladder — the
  recompilation storm the ladder exists to prevent — and raises
  ``CompileBudgetError`` naming the offending root.  Checked at the
  same quiesce points as block conservation.

- ``SKYTPU_SHARD_SANITIZER=1`` — ``check_shard_layout(engine)``
  asserts, at the same quiesce points, that the committed layouts of
  the jit roots' live inputs match the declared sharding registry
  (``analysis.shard_contract.REGISTRY``) for the engine's active mesh:
  every KV-cache leaf carries exactly the declared
  ``named_sharding(mesh, None, 'kv_heads', None, None)`` (mesh-fitted,
  like placement itself), every param leaf is committed to THIS mesh,
  and under ``tensor>1`` the param tree is not silently
  fully-replicated — the HBM blow-up the static SHARD002 rule proves
  absent.  Violations raise ``ShardLayoutError``; a mesh-less engine
  is a no-op.

``SKYTPU_SANITIZERS=1`` enables all four.  Lock *names* are roles shared
across instances (``'infer.engine._lock'``), so an order inversion
between two engine instances is still an inversion — the discipline is
per role, matching how the code is written.
"""
import os
import threading
from typing import Any, Dict, List, Optional, Set

_TRUTHY = frozenset({'1', 'true', 'yes', 'on'})


def _env_on(name: str) -> bool:
    return os.environ.get(name, '').strip().lower() in _TRUTHY


def lock_sanitizer_enabled() -> bool:
    return _env_on('SKYTPU_LOCK_SANITIZER') or _env_on('SKYTPU_SANITIZERS')


def block_sanitizer_enabled() -> bool:
    return _env_on('SKYTPU_BLOCK_SANITIZER') or _env_on('SKYTPU_SANITIZERS')


def compile_sanitizer_enabled() -> bool:
    return (_env_on('SKYTPU_COMPILE_SANITIZER') or
            _env_on('SKYTPU_SANITIZERS'))


def shard_sanitizer_enabled() -> bool:
    return (_env_on('SKYTPU_SHARD_SANITIZER') or
            _env_on('SKYTPU_SANITIZERS'))


class LockOrderError(RuntimeError):
    """A lock acquisition violates the global acquisition order."""


class BlockLeakError(RuntimeError):
    """The paged pool's refcount conservation invariant is broken."""


class CompileBudgetError(RuntimeError):
    """A jit root compiled more variants than the provable bound."""


class ShardLayoutError(RuntimeError):
    """A live buffer's committed sharding drifted from the declared
    registry (or the param tree replicated under tensor>1)."""


# --------------------------------------------------------------- lock order

class _OrderGraph:
    """Global held->acquired edge graph + per-thread held stacks."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # role name -> roles acquired at least once while it was held
        self.edges: Dict[str, Set[str]] = {}
        self._tls = threading.local()

    def _stack(self) -> List[str]:
        st = getattr(self._tls, 'stack', None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """BFS path src -> ... -> dst through edges, else None.
        Caller holds self._mu."""
        if src == dst:
            return [src]
        parents: Dict[str, str] = {}
        frontier = [src]
        while frontier:
            nxt: List[str] = []
            for node in frontier:
                for succ in self.edges.get(node, ()):
                    if succ in parents or succ == src:
                        continue
                    parents[succ] = node
                    if succ == dst:
                        path = [dst]
                        while path[-1] != src:
                            path.append(parents[path[-1]])
                        return list(reversed(path))
                    nxt.append(succ)
            frontier = nxt
        return None

    def before_acquire(self, name: str) -> None:
        """Called BEFORE touching the real lock: self-deadlock check."""
        if name in self._stack():
            raise LockOrderError(
                f"thread re-acquiring non-reentrant lock '{name}' it "
                'already holds (would deadlock); mark the helper '
                "'# locked:' and drop the inner acquisition")

    def after_acquire(self, name: str) -> None:
        stack = self._stack()
        cycle: Optional[List[str]] = None
        with self._mu:
            for held in stack:
                self.edges.setdefault(held, set()).add(name)
            for held in stack:
                path = self._path(name, held)
                if path is not None:
                    cycle = path + [name]
                    break
        stack.append(name)
        if cycle is not None:
            raise LockOrderError(
                'lock-order inversion: acquired '
                f"'{name}' while holding '{cycle[-2]}', but the reverse "
                f"order was also observed (cycle: {' -> '.join(cycle)})")

    def on_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def snapshot(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self.edges.items()}

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()


_GRAPH = _OrderGraph()


def lock_order_edges() -> Dict[str, Set[str]]:
    """Copy of the observed acquisition-order graph (for tests/debug)."""
    return _GRAPH.snapshot()


def reset_lock_order() -> None:
    """Drop all recorded edges (tests only — the graph is global)."""
    _GRAPH.reset()


class InstrumentedLock:
    """Duck-types threading.Lock; feeds the global order graph."""

    __slots__ = ('_lock', 'name')

    def __init__(self, lock: Any, name: str) -> None:
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        _GRAPH.before_acquire(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            try:
                _GRAPH.after_acquire(self.name)
            except LockOrderError:
                # Leave no half-tracked state: the violation aborts the
                # acquisition entirely so a test catching the error
                # does not leak a held lock.
                self._lock.release()
                raise
        return ok

    def release(self) -> None:
        _GRAPH.on_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> 'InstrumentedLock':
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f'<InstrumentedLock {self.name!r} {self._lock!r}>'


def instrument_lock(lock: Any, name: str) -> Any:
    """Wrap ``lock`` for order checking, or return it unchanged when
    the sanitizer is off.  ``name`` is the lock's ROLE (e.g.
    ``'serve.load_balancer._stats_lock'``), shared across instances."""
    if not lock_sanitizer_enabled():
        return lock
    return InstrumentedLock(lock, name)


# --------------------------------------------------------------- block leak

def check_block_conservation(engine: Any) -> Optional[Dict[str, int]]:
    """Verify refcount conservation on a paged engine's block pool.

    For every block b in [1, num_blocks): ``_block_refs[b]`` must equal
    the number of slot-table entries (within each slot's
    ``_slot_nblocks``) + radix nodes + registered-prefix entries
    holding b; the dump block 0 carries exactly its permanent ref plus
    any table entries; and the free list is exactly the zero-ref
    blocks, without duplicates.  Acquires ``engine._lock`` itself —
    call from OUTSIDE the lock, at a quiesce point.

    Returns a small accounting dict on success (None for non-paged
    engines); raises BlockLeakError on violation.
    """
    if not getattr(engine, '_paged', False):
        return None
    with engine._lock:
        n = int(engine._num_blocks)
        refs = [int(r) for r in engine._block_refs]
        expected = [0] * n
        expected[0] = 1                     # permanent dump-block ref
        slot_refs = 0
        for slot in range(engine._tables_np.shape[0]):
            k = int(engine._slot_nblocks[slot])
            for b in engine._tables_np[slot, :k]:
                expected[int(b)] += 1
                slot_refs += 1
        radix_refs = 0
        if getattr(engine, '_radix', None) is not None:
            for node in engine._radix.walk():
                expected[int(node.block)] += 1
                radix_refs += 1
        prefix_refs = 0
        for entry in engine._prefixes.values():
            for b in entry.get('blocks', ()):
                expected[int(b)] += 1
                prefix_refs += 1
        free = [int(b) for b in engine._free_blocks]
        # Host tier (when armed): its byte ledger is the tier-boundary
        # half of the conservation law — a spilled entry whose bytes
        # drifted from the ledger is a leak ACROSS the boundary the
        # device-side refcounts can no longer see.
        tier = getattr(engine, '_host_tier', None)
        tier_errors = list(tier.audit()) if tier is not None else []
        tier_entries = tier.entries if tier is not None else 0
    errors: List[str] = tier_errors
    bad = [(b, refs[b], expected[b]) for b in range(n)
           if refs[b] != expected[b]]
    for b, got, want in bad[:5]:
        errors.append(f'block {b}: refcount {got} != {want} referers '
                      '(slot tables + radix + prefixes'
                      f'{" + dump ref" if b == 0 else ""})')
    if len(bad) > 5:
        errors.append(f'... and {len(bad) - 5} more blocks')
    if len(set(free)) != len(free):
        errors.append(f'free list contains duplicates '
                      f'({len(free) - len(set(free))})')
    if 0 in free:
        errors.append('dump block 0 is on the free list')
    zero_ref = {b for b in range(1, n) if refs[b] == 0}
    free_set = set(free) - {0}
    leaked = sorted(zero_ref - free_set)
    phantom = sorted(free_set - zero_ref)
    if leaked:
        errors.append(f'leaked blocks (refcount 0, not on free list): '
                      f'{leaked[:10]}')
    if phantom:
        errors.append(f'free-listed blocks with nonzero refcount: '
                      f'{phantom[:10]}')
    if errors:
        raise BlockLeakError(
            'block conservation violated:\n  ' + '\n  '.join(errors))
    return {'blocks': n - 1, 'free': len(free), 'slot_refs': slot_refs,
            'radix_refs': radix_refs, 'prefix_refs': prefix_refs,
            'host_tier_entries': tier_entries}


def maybe_check_block_conservation(engine: Any) -> None:
    """Serving-loop quiesce hook: no-op unless the gate is on."""
    if block_sanitizer_enabled():
        check_block_conservation(engine)


# ------------------------------------------------------------ compile budget

def check_compile_budget(engine: Any) -> Dict[str, Any]:
    """Assert measured XLA compile counts against the static bounds.

    For every jit root the COMPILE pass discovers in the engine's
    source, ``fn._cache_size()`` (the root's accumulated compilation
    count) must not exceed the provable worst case under THIS engine's
    config.  Exceeding it means a shape dimension reached the root
    without going through a bucketing ladder.  Returns
    ``{root: (measured, bound)}``; raises CompileBudgetError on any
    violation.
    """
    from skypilot_tpu.analysis import compile_budget
    counts = compile_budget.check_engine_budget(engine)
    over = [(name, measured, bound)
            for name, (measured, bound) in sorted(counts.items())
            if measured > bound]
    if over:
        lines = [f'{name}: measured {measured} compiles > provable '
                 f'bound {bound}' for name, measured, bound in over]
        raise CompileBudgetError(
            'compile budget exceeded (a shape dimension escaped the '
            'bucketing ladder):\n  ' + '\n  '.join(lines))
    return counts


def maybe_check_compile_budget(engine: Any) -> None:
    """Quiesce hook twin of maybe_check_block_conservation."""
    if compile_sanitizer_enabled():
        check_compile_budget(engine)


# ------------------------------------------------------------- shard layout

def _shard_shape(sharding: Any, shape: Any) -> Any:
    return tuple(sharding.shard_shape(tuple(shape)))


def check_shard_layout(engine: Any) -> Dict[str, int]:
    """Assert the engine's live jit-root inputs hold their DECLARED
    layouts on the active mesh.

    The persistent roots' committed inputs are the param tree and the
    KV cache (everything else is per-dispatch); their ``.sharding``
    must match ``analysis.shard_contract.REGISTRY``'s declared specs,
    resolved through the same logical-rule table and mesh-fitting as
    placement itself:

    - every cache leaf: exactly ``named_sharding(mesh, None,
      'kv_heads', None, None)`` fitted to the leaf shape (indivisible
      dims replicate, engine._fit_sharding);
    - a PAGED pool additionally proves its geometry: every per-layer
      leaf is ``[num_blocks, Hkv, block_size, D]`` (the allocator's
      global block-id space — dim 0 must match ``engine._num_blocks``
      exactly, or host tables index off the end of the device pool)
      and its committed shard holds ``Hkv // tp`` heads per chip, the
      head-local layout the chip-local gathers rely on;
    - every param leaf: committed to THIS mesh (a leaf resharded onto
      a stray mesh, or left on one device, is drift);
    - under ``tensor>1``: at least one param leaf actually sharded —
      a fully-replicated tree is the silent HBM blow-up.

    Returns an accounting dict ({} when the engine has no mesh);
    raises ShardLayoutError on drift.
    """
    mesh = getattr(engine, '_mesh', None)
    if mesh is None:
        return {}
    import jax

    from skypilot_tpu.parallel import mesh as mesh_lib
    errors: List[str] = []
    declared = mesh_lib.named_sharding(mesh, None, 'kv_heads', None,
                                       None)
    mesh_devices = set(mesh.devices.flat)
    tensor = dict(mesh.shape).get('tensor', 1)
    paged = bool(getattr(engine, '_paged', False))
    # Paged requires the llama family, so num_kv_heads exists there;
    # other families (dense-only) never reach the geometry checks.
    hkv = engine.model_config.num_kv_heads if paged else 0
    cache_leaves = 0
    for li, (k, v) in enumerate(getattr(engine, 'cache', ()) or ()):
        for tag, leaf in (('k', k), ('v', v)):
            cache_leaves += 1
            expect = engine._fit_sharding(leaf.shape, declared)
            got = getattr(leaf, 'sharding', None)
            if got is None or \
                    _shard_shape(got, leaf.shape) != \
                    _shard_shape(expect, leaf.shape):
                errors.append(
                    f'cache layer {li} {tag}: committed sharding '
                    f'{got} != declared {expect.spec} '
                    f'(registry: P(None, kv_heads, None, None))')
                continue
            if not paged:
                continue
            # Paged-pool geometry: the host allocator hands out GLOBAL
            # block ids in [0, _num_blocks) and the radix tree shares
            # them by refcount — a pool whose dim 0 drifted from the
            # allocator's id space corrupts silently (tables gather
            # the wrong pages), so assert it exactly, along with the
            # block width and the per-chip head count the chip-local
            # gather relies on.
            if tuple(leaf.shape) != (engine._num_blocks, hkv,
                                     engine.cfg.kv_block_size,
                                     engine.model_config.head_dim_):
                errors.append(
                    f'paged pool layer {li} {tag}: leaf shape '
                    f'{tuple(leaf.shape)} != allocator geometry '
                    f'({engine._num_blocks}, {hkv}, '
                    f'{engine.cfg.kv_block_size}, '
                    f'{engine.model_config.head_dim_})')
            elif hkv % max(tensor, 1) == 0 and \
                    _shard_shape(got, leaf.shape)[1] != hkv // tensor:
                errors.append(
                    f'paged pool layer {li} {tag}: committed shard '
                    f'holds {_shard_shape(got, leaf.shape)[1]} kv '
                    f'heads per chip, declared layout owns '
                    f'{hkv // tensor} (Hkv={hkv} over tensor='
                    f'{tensor})')
    param_leaves = jax.tree.leaves(getattr(engine, 'params', {}))
    sharded = 0
    for leaf in param_leaves:
        sh = getattr(leaf, 'sharding', None)
        if sh is None:
            continue
        leaf_devices = set(getattr(sh, 'device_set', ()))
        if leaf_devices and leaf_devices != mesh_devices:
            errors.append(
                f'param leaf committed to {len(leaf_devices)} '
                f'device(s) outside the active mesh '
                f'({len(mesh_devices)} devices)')
            continue
        if _shard_shape(sh, leaf.shape) != tuple(leaf.shape):
            sharded += 1
    if tensor > 1 and param_leaves and sharded == 0:
        errors.append(
            f'param tree fully replicated across a tensor={tensor} '
            'mesh: every leaf holds the whole weight (HBM blow-up); '
            'params must be born sharded through the logical rules')
    if errors:
        raise ShardLayoutError(
            'shard layout drifted from the declared registry:\n  '
            + '\n  '.join(errors[:8]))
    return {'cache_leaves': cache_leaves,
            'paged_pool_leaves': cache_leaves if paged else 0,
            'param_leaves': len(param_leaves),
            'param_leaves_sharded': sharded,
            'tensor_degree': tensor}


def maybe_check_shard_layout(engine: Any) -> None:
    """Quiesce hook twin for the shard-layout sanitizer."""
    if shard_sanitizer_enabled():
        check_shard_layout(engine)
