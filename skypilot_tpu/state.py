"""Local persistent state: SQLite DB at ``$SKYTPU_HOME/state.db``.

Parity: sky/global_user_state.py:34 — tables for clusters (pickled handle,
status, autostop, owner), cluster history, storage, and a config KV store
(enabled clouds cache).  No long-lived daemon: every CLI/SDK call opens the
DB directly; concurrency is handled with WAL mode + per-cluster file locks
(utils/locks.py).
"""
import json
import os
import pickle
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu import logsys
from skypilot_tpu.status_lib import ClusterStatus, StorageStatus
from skypilot_tpu.utils import common

logger = logsys.init_logger(__name__)

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS clusters (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT,
    autostop INTEGER DEFAULT -1,
    to_down INTEGER DEFAULT 0,
    owner TEXT DEFAULT NULL,
    metadata TEXT DEFAULT '{}',
    cluster_hash TEXT DEFAULT NULL,
    status_updated_at INTEGER DEFAULT 0);
CREATE TABLE IF NOT EXISTS cluster_history (
    cluster_hash TEXT PRIMARY KEY,
    name TEXT,
    num_nodes INTEGER,
    requested_resources BLOB,
    launched_resources BLOB,
    usage_intervals BLOB);
CREATE TABLE IF NOT EXISTS storage (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT);
CREATE TABLE IF NOT EXISTS config (
    key TEXT PRIMARY KEY,
    value TEXT);
"""

_local = threading.local()


def _db() -> sqlite3.Connection:
    """One connection per (thread, db-path); creates schema on first use."""
    path = common.state_db_path()
    conn = getattr(_local, 'conn', None)
    if conn is not None and getattr(_local, 'path', None) == path:
        return conn
    common.ensure_dir(os.path.dirname(path))
    conn = sqlite3.connect(path, timeout=10.0)
    conn.execute('PRAGMA journal_mode=WAL')
    conn.executescript(_CREATE_SQL)
    conn.commit()
    _local.conn = conn
    _local.path = path
    return conn


def reset_for_tests() -> None:
    """Drop the cached connection so SKYTPU_HOME changes take effect."""
    _local.conn = None
    _local.path = None


# ----------------------------------------------------------------- clusters


def add_or_update_cluster(cluster_name: str,
                          cluster_handle: Any,
                          requested_resources: Optional[set],
                          ready: bool,
                          is_launch: bool = True,
                          owner: Optional[str] = None) -> None:
    """Record a (re)provisioned cluster.  Parity:
    sky/global_user_state.py:139.

    owner: the creating cloud identity (JSON list from
    Cloud.get_active_user_identity) — consulted by
    backend_utils.check_owner_identity on mutating ops.  Kept on
    conflict (first writer wins) unless explicitly given."""
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    now = int(time.time())
    handle_blob = pickle.dumps(cluster_handle)
    last_use = _current_command() if is_launch else None
    cluster_hash = _get_hash_for_existing_cluster(cluster_name) or str(
        uuid.uuid4())
    conn = _db()
    with conn:
        row = conn.execute('SELECT launched_at FROM clusters WHERE name=?',
                           (cluster_name,)).fetchone()
        launched_at = now if (is_launch or row is None) else row[0]
        conn.execute(
            'INSERT INTO clusters (name, launched_at, handle, last_use,'
            ' status, autostop, to_down, owner, metadata, cluster_hash,'
            ' status_updated_at)'
            ' VALUES (?,?,?,?,?,'
            '  COALESCE((SELECT autostop FROM clusters WHERE name=?), -1),'
            '  COALESCE((SELECT to_down FROM clusters WHERE name=?), 0),'
            '  COALESCE(?, (SELECT owner FROM clusters WHERE name=?), ?),'
            '  COALESCE((SELECT metadata FROM clusters WHERE name=?), \'{}\'),'
            '  ?, ?)'
            ' ON CONFLICT(name) DO UPDATE SET launched_at=excluded.launched_at,'
            ' handle=excluded.handle,'
            ' last_use=COALESCE(excluded.last_use, last_use),'
            ' status=excluded.status, cluster_hash=excluded.cluster_hash,'
            ' owner=COALESCE(?, owner),'
            ' status_updated_at=excluded.status_updated_at',
            (cluster_name, launched_at, handle_blob, last_use, status.value,
             cluster_name, cluster_name, owner, cluster_name,
             common.get_user_hash(), cluster_name, cluster_hash, now, owner))
        if requested_resources is not None:
            _record_history(conn, cluster_name, cluster_hash,
                            cluster_handle, requested_resources, now)


def _record_history(conn, name, cluster_hash, handle, requested_resources,
                    now) -> None:
    launched = getattr(handle, 'launched_resources', None)
    num_nodes = getattr(handle, 'launched_nodes', None)
    row = conn.execute(
        'SELECT usage_intervals FROM cluster_history WHERE cluster_hash=?',
        (cluster_hash,)).fetchone()
    intervals: List = pickle.loads(row[0]) if row and row[0] else []
    if not intervals or intervals[-1][1] is not None:
        intervals.append((now, None))
    conn.execute(
        'INSERT OR REPLACE INTO cluster_history'
        ' (cluster_hash, name, num_nodes, requested_resources,'
        '  launched_resources, usage_intervals) VALUES (?,?,?,?,?,?)',
        (cluster_hash, name, num_nodes, pickle.dumps(requested_resources),
         pickle.dumps(launched), pickle.dumps(intervals)))


def update_cluster_status(cluster_name: str, status: ClusterStatus) -> None:
    with _db() as conn:
        conn.execute(
            'UPDATE clusters SET status=?, status_updated_at=? WHERE name=?',
            (status.value, int(time.time()), cluster_name))


def update_cluster_handle(cluster_name: str, handle: Any) -> None:
    with _db() as conn:
        conn.execute('UPDATE clusters SET handle=? WHERE name=?',
                     (pickle.dumps(handle), cluster_name))


def update_last_use(cluster_name: str) -> None:
    with _db() as conn:
        conn.execute('UPDATE clusters SET last_use=? WHERE name=?',
                     (_current_command(), cluster_name))


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    """On stop: clear stale network info; on terminate: drop the record and
    close the usage interval."""
    conn = _db()
    with conn:
        if terminate:
            row = conn.execute(
                'SELECT cluster_hash FROM clusters WHERE name=?',
                (cluster_name,)).fetchone()
            if row and row[0]:
                hrow = conn.execute(
                    'SELECT usage_intervals FROM cluster_history'
                    ' WHERE cluster_hash=?', (row[0],)).fetchone()
                if hrow and hrow[0]:
                    intervals = pickle.loads(hrow[0])
                    if intervals and intervals[-1][1] is None:
                        intervals[-1] = (intervals[-1][0], int(time.time()))
                        conn.execute(
                            'UPDATE cluster_history SET usage_intervals=?'
                            ' WHERE cluster_hash=?',
                            (pickle.dumps(intervals), row[0]))
            conn.execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
        else:
            row = conn.execute('SELECT handle FROM clusters WHERE name=?',
                               (cluster_name,)).fetchone()
            if row is not None:
                handle = pickle.loads(row[0])
                if hasattr(handle, 'stable_internal_external_ips'):
                    handle.stable_internal_external_ips = None
                conn.execute(
                    'UPDATE clusters SET handle=?, status=? WHERE name=?',
                    (pickle.dumps(handle), ClusterStatus.STOPPED.value,
                     cluster_name))


def get_handle_from_cluster_name(cluster_name: str) -> Optional[Any]:
    row = _db().execute('SELECT handle FROM clusters WHERE name=?',
                        (cluster_name,)).fetchone()
    return pickle.loads(row[0]) if row else None


def get_cluster_from_name(cluster_name: str) -> Optional[Dict[str, Any]]:
    row = _db().execute('SELECT * FROM clusters WHERE name=?',
                        (cluster_name,)).fetchone()
    return _row_to_record(row) if row else None


def get_clusters() -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_row_to_record(r) for r in rows]


def _row_to_record(row) -> Dict[str, Any]:
    (name, launched_at, handle, last_use, status, autostop, to_down, owner,
     metadata, cluster_hash, status_updated_at) = row
    return {
        'name': name,
        'launched_at': launched_at,
        'handle': pickle.loads(handle),
        'last_use': last_use,
        'status': ClusterStatus(status),
        'autostop': autostop,
        'to_down': bool(to_down),
        'owner': owner,
        'metadata': json.loads(metadata or '{}'),
        'cluster_hash': cluster_hash,
        'status_updated_at': status_updated_at,
    }


def set_cluster_owner(cluster_name: str, owner: str) -> None:
    """Record the creating cloud identity (JSON list) — the backfill
    path of backend_utils.check_owner_identity."""
    conn = _db()
    with conn:
        conn.execute('UPDATE clusters SET owner=? WHERE name=?',
                     (owner, cluster_name))


def set_cluster_autostop(cluster_name: str, idle_minutes: int,
                         to_down: bool) -> None:
    with _db() as conn:
        conn.execute('UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
                     (idle_minutes, int(to_down), cluster_name))


def set_cluster_metadata(cluster_name: str, metadata: Dict[str, Any]) -> None:
    with _db() as conn:
        conn.execute('UPDATE clusters SET metadata=? WHERE name=?',
                     (json.dumps(metadata), cluster_name))


def get_cluster_history() -> List[Dict[str, Any]]:
    rows = _db().execute('SELECT * FROM cluster_history').fetchall()
    out = []
    for (cluster_hash, name, num_nodes, requested, launched,
         intervals) in rows:
        out.append({
            'cluster_hash': cluster_hash,
            'name': name,
            'num_nodes': num_nodes,
            'requested_resources':
                pickle.loads(requested) if requested else None,
            'launched_resources': pickle.loads(launched) if launched else None,
            'usage_intervals': pickle.loads(intervals) if intervals else [],
        })
    return out


def _get_hash_for_existing_cluster(cluster_name: str) -> Optional[str]:
    row = _db().execute('SELECT cluster_hash FROM clusters WHERE name=?',
                        (cluster_name,)).fetchone()
    return row[0] if row else None


def _current_command() -> str:
    import sys
    return ' '.join(sys.argv)


# ------------------------------------------------------------------ storage


def add_or_update_storage(storage_name: str, storage_handle: Any,
                          storage_status: StorageStatus) -> None:
    with _db() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO storage'
            ' (name, launched_at, handle, last_use, status) VALUES (?,?,?,?,?)',
            (storage_name, int(time.time()), pickle.dumps(storage_handle),
             _current_command(), storage_status.value))


def set_storage_status(storage_name: str, status: StorageStatus) -> None:
    with _db() as conn:
        conn.execute('UPDATE storage SET status=? WHERE name=?',
                     (status.value, storage_name))


def remove_storage(storage_name: str) -> None:
    with _db() as conn:
        conn.execute('DELETE FROM storage WHERE name=?', (storage_name,))


def get_storage() -> List[Dict[str, Any]]:
    rows = _db().execute('SELECT * FROM storage').fetchall()
    return [{
        'name': name,
        'launched_at': launched_at,
        'handle': pickle.loads(handle),
        'last_use': last_use,
        'status': StorageStatus(status),
    } for name, launched_at, handle, last_use, status in rows]


def get_storage_handle(storage_name: str) -> Optional[Any]:
    row = _db().execute('SELECT handle FROM storage WHERE name=?',
                        (storage_name,)).fetchone()
    return pickle.loads(row[0]) if row else None


# ---------------------------------------------------------------- config KV


def kv_set(key: str, value: Any) -> None:
    with _db() as conn:
        conn.execute('INSERT OR REPLACE INTO config (key, value) VALUES (?,?)',
                     (key, json.dumps(value)))


def kv_get(key: str, default: Any = None) -> Any:
    row = _db().execute('SELECT value FROM config WHERE key=?',
                        (key,)).fetchone()
    return json.loads(row[0]) if row else default


def set_enabled_clouds(clouds: List[str]) -> None:
    kv_set('enabled_clouds', clouds)


def get_cached_enabled_clouds() -> List[str]:
    return kv_get('enabled_clouds', [])
