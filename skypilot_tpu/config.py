"""Layered user configuration (``~/.skytpu/config.yaml``).

Parity: sky/skypilot_config.py:84-257 — nested dot-path get/set, loaded once
at import, overridable via the ``SKYTPU_CONFIG`` env var.  Example::

    gcp:
      project_id: my-project
    jobs:
      controller:
        resources:
          cpus: 8+
    serve:
      controller:
        resources:
          cloud: gcp
"""
import copy
import os
import threading
from typing import Any, Dict, Optional

import yaml

from skypilot_tpu import logsys
from skypilot_tpu.utils import common

logger = logsys.init_logger(__name__)

ENV_VAR_CONFIG_PATH = 'SKYTPU_CONFIG'

_dict: Optional[Dict[str, Any]] = None
_loaded_path: Optional[str] = None
_lock = threading.Lock()


def _config_path() -> str:
    env = os.environ.get(ENV_VAR_CONFIG_PATH)
    if env:
        return os.path.expanduser(env)
    return os.path.join(common.home_dir(), 'config.yaml')


def _load() -> Dict[str, Any]:
    global _dict, _loaded_path
    path = _config_path()
    with _lock:
        if _dict is not None and _loaded_path == path:
            return _dict
        _dict = {}
        _loaded_path = path
        if os.path.exists(path):
            try:
                with open(path, 'r', encoding='utf-8') as f:
                    loaded = yaml.safe_load(f)
                if loaded is not None:
                    if not isinstance(loaded, dict):
                        raise ValueError(
                            f'Config file {path} must contain a mapping.')
                    _dict = loaded
            except yaml.YAMLError as e:
                raise ValueError(f'Invalid config YAML at {path}: {e}') from e
        return _dict


def reload() -> None:
    """Force re-read (tests change SKYTPU_HOME / SKYTPU_CONFIG)."""
    global _dict, _loaded_path
    with _lock:
        _dict = None
        _loaded_path = None


def get_nested(keys, default_value: Any = None) -> Any:
    """config.get_nested(('jobs','controller','resources')) style lookup."""
    cur: Any = _load()
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default_value
        cur = cur[k]
    return copy.deepcopy(cur)


def set_nested(keys, value: Any) -> Dict[str, Any]:
    """Return a copy of the config with keys set (does not persist)."""
    base = copy.deepcopy(_load())
    cur = base
    for k in keys[:-1]:
        cur = cur.setdefault(k, {})
    cur[keys[-1]] = value
    return base


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_load())


def loaded() -> bool:
    return bool(_load())
