"""The `skytpu` command-line interface.

Parity: sky/cli.py — launch/exec/status/start/stop/down/autostop/queue/
logs/cancel/check/show-tpus/cost-report/optimize plus the `storage`,
`jobs`, and `serve` sub-groups.  Same shape (click groups, natural
ordering, -y confirmation bypass, CLI-flag -> Resources overrides,
entrypoint = YAML path or inline command), TPU-first surface (`show-tpus`
lists pod-slice shapes instead of GPU counts).
"""
import os
import time
from typing import Any, Dict, List, Optional

import click

from skypilot_tpu import exceptions


class _NaturalOrderGroup(click.Group):
    """Commands listed in definition order (parity: sky/cli.py)."""

    def list_commands(self, ctx):
        return self.commands.keys()


def _fmt_duration(seconds: Optional[float]) -> str:
    if not seconds:
        return '-'
    seconds = int(seconds)
    if seconds < 60:
        return f'{seconds}s'
    if seconds < 3600:
        return f'{seconds // 60}m'
    if seconds < 86400:
        return f'{seconds // 3600}h {seconds % 3600 // 60}m'
    return f'{seconds // 86400}d {seconds % 86400 // 3600}h'


def _fmt_ts(ts: Optional[float]) -> str:
    if not ts:
        return '-'
    return time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(ts))


def _table(headers: List[str], rows: List[List[Any]]) -> str:
    cells = [[str(c) for c in row] for row in rows]
    widths = [
        max(len(h), *(len(r[i]) for r in cells)) if cells else len(h)
        for i, h in enumerate(headers)
    ]
    lines = ['  '.join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in cells:
        lines.append('  '.join(c.ljust(w) for c, w in zip(row, widths)))
    return '\n'.join(lines)


def _make_task(entrypoint: tuple, name: Optional[str],
               workdir: Optional[str], cloud: Optional[str],
               tpus: Optional[str], cpus: Optional[str],
               memory: Optional[str], use_spot: Optional[bool],
               region: Optional[str], zone: Optional[str],
               num_nodes: Optional[int], env: tuple):
    """Entrypoint = a task YAML path or an inline command, with CLI flags
    overriding the YAML (parity: sky/cli.py:475,704)."""
    from skypilot_tpu import Resources, Task
    entry = ' '.join(entrypoint).strip()
    if entry.endswith(('.yaml', '.yml')):
        # YAML-looking entrypoints must exist: a typo'd path silently
        # running as a shell command would provision a cluster for it.
        path = os.path.expanduser(entry)
        if not os.path.isfile(path):
            raise click.UsageError(f'Task YAML not found: {entry}')
        task = Task.from_yaml(path)
    else:
        if not entry:
            raise click.UsageError(
                'ENTRYPOINT must be a task YAML or an inline command.')
        task = Task(run=entry)
    if name is not None:
        task.name = name
    if workdir is not None:
        task.workdir = workdir
    if num_nodes is not None:
        task.num_nodes = num_nodes
    if env:
        task.update_envs(list(env))

    override: Dict[str, Any] = {}
    if cloud is not None:
        override['cloud'] = cloud
    if tpus is not None:
        override['accelerator'] = tpus
    if cpus is not None:
        override['cpus'] = cpus
    if memory is not None:
        override['memory'] = memory
    if use_spot is not None:
        override['use_spot'] = use_spot
    if region is not None:
        override['region'] = region
    if zone is not None:
        override['zone'] = zone
    if override:
        base = list(task.resources)
        if len(base) == 1:
            task.set_resources(base[0].copy(**override))
        else:
            task.set_resources([r.copy(**override) for r in base])
    return task


def _resource_flags(f=None, *, include_name=True):
    if f is None:
        return lambda g: _resource_flags(g, include_name=include_name)
    opts = [
        click.option('--workdir', default=None,
                     help='Directory synced to every host.'),
        click.option('--cloud', default=None, help='Cloud (gcp|local).'),
        click.option('--tpus', '--gpus', 'tpus', default=None,
                     help='TPU slice, e.g. tpu-v5e-8, v6e-64.'),
        click.option('--cpus', default=None, help="vCPUs, e.g. '8+'."),
        click.option('--memory', default=None, help="GiB, e.g. '32+'."),
        click.option('--use-spot/--no-use-spot', 'use_spot', default=None,
                     help='Preemptible capacity.'),
        click.option('--region', default=None),
        click.option('--zone', default=None),
        click.option('--num-nodes', type=int, default=None,
                     help='Number of slices (gang width multiplier).'),
        click.option('--env', multiple=True, help='KEY=VALUE (repeat).'),
    ]
    if include_name:
        opts.insert(0, click.option('--name', '-n', default=None,
                                    help='Task name (overrides YAML).'))
    for opt in reversed(opts):
        f = opt(f)
    return f


@click.group(cls=_NaturalOrderGroup)
@click.version_option(None, '--version', '-v', package_name=None,
                      message='%(prog)s %(version)s',
                      prog_name='skytpu')
def cli():
    """skytpu: launch and manage tasks on TPU pod slices."""


# ------------------------------------------------------------------ launch


@cli.command()
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@_resource_flags
@click.option('--detach-run', '-d', is_flag=True, default=False,
              help='Return after job submission without tailing logs.')
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False,
              help='Autodown (terminate) when idle (requires -i).')
@click.option('--retry-until-up', '-r', is_flag=True, default=False)
@click.option('--fast', is_flag=True, default=False,
              help='Skip provisioning/setup if the cluster is UP.')
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def launch(entrypoint, cluster, name, workdir, cloud, tpus, cpus, memory,
           use_spot, region, zone, num_nodes, env, detach_run,
           idle_minutes_to_autostop, down, retry_until_up, fast, dryrun,
           yes):
    """Provision (or reuse) a cluster and run ENTRYPOINT on it."""
    from skypilot_tpu import execution
    task = _make_task(entrypoint, name, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    if not yes and not dryrun:
        plan = next(iter(task.resources))
        click.confirm(
            f'Launching task {task.name or "(unnamed)"!r} on '
            f'{cluster or "a new cluster"} ({plan}). Proceed?',
            default=True, abort=True)
    job_id = execution.launch(
        task, cluster_name=cluster, dryrun=dryrun, detach_run=detach_run,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        retry_until_up=retry_until_up, fast=fast)
    if job_id is not None:
        click.echo(f'Job submitted: {job_id}')


@cli.command('exec')
@click.argument('cluster')
@click.argument('entrypoint', nargs=-1, required=True)
@_resource_flags
@click.option('--detach-run', '-d', is_flag=True, default=False)
def exec_cmd(cluster, entrypoint, name, workdir, cloud, tpus, cpus, memory,
             use_spot, region, zone, num_nodes, env, detach_run):
    """Submit a job to an existing cluster (skips provision/setup)."""
    from skypilot_tpu import execution
    task = _make_task(entrypoint, name, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    job_id = execution.exec_(task, cluster, detach_run=detach_run)
    if job_id is not None:
        click.echo(f'Job submitted: {job_id}')


# ------------------------------------------------------------------ status


@cli.command()
@click.option('--refresh', '-r', is_flag=True, default=False,
              help='Reconcile against live cloud state first.')
def status(refresh):
    """Show clusters."""
    from skypilot_tpu import core
    records = core.status(refresh=refresh)
    if not records:
        click.echo('No existing clusters.')
        return
    rows = []
    for r in records:
        handle = r.get('handle')
        resources = '-'
        if handle is not None and handle.launched_resources is not None:
            resources = str(handle.launched_resources)
        autostop = r.get('autostop', -1)
        rows.append([
            r['name'], resources,
            r['status'].value if hasattr(r['status'], 'value') else
            r['status'],
            _fmt_ts(r.get('launched_at')),
            f'{autostop}m' + ('(down)' if r.get('to_down') else '')
            if autostop is not None and autostop >= 0 else '-',
        ])
    click.echo(_table(['NAME', 'RESOURCES', 'STATUS', 'LAUNCHED',
                       'AUTOSTOP'], rows))


@cli.command()
@click.argument('cluster')
@click.option('--retry-until-up', '-r', is_flag=True, default=False)
def start(cluster, retry_until_up):
    """Restart a stopped cluster."""
    from skypilot_tpu import core
    core.start(cluster, retry_until_up=retry_until_up)
    click.echo(f'Cluster {cluster!r} started.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(clusters, yes):
    """Stop cluster(s) (restartable; TPU slices usually cannot stop)."""
    from skypilot_tpu import core
    for name in clusters:
        if not yes:
            click.confirm(f'Stop cluster {name!r}?', default=True,
                          abort=True)
        core.stop(name)
        click.echo(f'Cluster {name!r} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--purge', is_flag=True, default=False,
              help='Remove local state even if cloud teardown fails.')
@click.option('--yes', '-y', is_flag=True, default=False)
def down(clusters, purge, yes):
    """Terminate cluster(s)."""
    from skypilot_tpu import core
    for name in clusters:
        if not yes:
            click.confirm(f'Terminate cluster {name!r}?', default=True,
                          abort=True)
        core.down(name, purge=purge)
        click.echo(f'Cluster {name!r} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, default=None,
              help='Idle minutes before autostop; -1 cancels.')
@click.option('--cancel', 'cancel_flag', is_flag=True, default=False)
@click.option('--down', is_flag=True, default=False,
              help='Terminate instead of stop when idle.')
def autostop(cluster, idle_minutes, cancel_flag, down):
    """Schedule stop/terminate-when-idle for a cluster."""
    from skypilot_tpu import core
    if cancel_flag:
        idle_minutes = -1
    if idle_minutes is None:
        raise click.UsageError('Provide --idle-minutes or --cancel.')
    core.autostop(cluster, idle_minutes, down_after_idle=down)
    if idle_minutes < 0:
        click.echo(f'Autostop cancelled on {cluster!r}.')
    else:
        click.echo(f'{cluster!r} will auto{"down" if down else "stop"} '
                   f'after {idle_minutes} idle minutes.')


# -------------------------------------------------------------------- jobs


@cli.command()
@click.argument('cluster')
def queue(cluster):
    """Show a cluster's job queue."""
    from skypilot_tpu import core
    jobs = core.queue(cluster)
    if not jobs:
        click.echo('No jobs.')
        return
    rows = [[
        j['job_id'],
        j.get('job_name') or '-',
        j.get('username') or '-',
        _fmt_ts(j.get('submitted_at')),
        j['status'],
        _fmt_duration((j.get('end_at') or time.time()) -
                      j['start_at'] if j.get('start_at') else None),
    ] for j in jobs]
    click.echo(_table(['ID', 'NAME', 'USER', 'SUBMITTED', 'STATUS',
                       'DURATION'], rows))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int, required=False, default=None)
@click.option('--no-follow', is_flag=True, default=False)
@click.option('--sync-down', '-s', is_flag=True, default=False,
              help='Download logs instead of streaming.')
def logs(cluster, job_id, no_follow, sync_down):
    """Tail (or download) a job's logs."""
    from skypilot_tpu import core
    if sync_down:
        path = core.download_logs(cluster, job_id)
        click.echo(f'Logs synced to {path}')
        return
    raise SystemExit(
        core.tail_logs(cluster, job_id=job_id, follow=not no_follow))


@cli.command()
@click.argument('cluster')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def cancel(cluster, job_ids, all_jobs, yes):
    """Cancel job(s) on a cluster."""
    from skypilot_tpu import core
    if not job_ids and not all_jobs:
        raise click.UsageError('Provide JOB_IDS or --all.')
    if not yes:
        what = 'all jobs' if all_jobs else f'job(s) {list(job_ids)}'
        click.confirm(f'Cancel {what} on {cluster!r}?', default=True,
                      abort=True)
    cancelled = core.cancel(cluster, job_ids=list(job_ids) or None,
                            all_jobs=all_jobs)
    click.echo(f'Cancelled: {cancelled or "none"}')


# ----------------------------------------------------------- environment


@cli.command()
def check():
    """Verify cloud credentials and enable clouds."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.check()
    if not enabled:
        raise SystemExit(1)


@cli.command('show-tpus')
@click.argument('accelerator', required=False, default=None)
@click.option('--all-regions', is_flag=True, default=False,
              help='Show per-zone availability and pricing.')
def show_tpus(accelerator, all_regions):
    """List TPU slice shapes, chips, and $/hr (analog of show-gpus)."""
    from skypilot_tpu import catalog
    if accelerator and all_regions:
        rows = []
        for region, zone in catalog.get_regions_zones(accelerator):
            od = catalog.get_hourly_cost(accelerator, use_spot=False,
                                         region=region, zone=zone)
            try:
                spot = catalog.get_hourly_cost(accelerator, use_spot=True,
                                               region=region, zone=zone)
                spot_s = f'{spot:.2f}'
            except exceptions.SkyTpuError:
                spot_s = '-'
            rows.append([accelerator, region, zone, f'{od:.2f}', spot_s])
        click.echo(_table(['TPU', 'REGION', 'ZONE', '$/HR', 'SPOT $/HR'],
                          rows))
        return
    listing = catalog.list_accelerators(name_filter=accelerator)
    rows = []
    for gen in sorted(listing):
        for info in listing[gen]:
            od = catalog.get_hourly_cost(info.accelerator, use_spot=False)
            rows.append([
                info.accelerator, info.chips, info.hosts,
                f'{info.total_tflops_bf16:.0f}', f'{od:.2f}'
            ])
    click.echo(_table(['TPU', 'CHIPS', 'HOSTS', 'BF16 TFLOPS', '$/HR'],
                      rows))


@cli.command('cost-report')
def cost_report():
    """Accumulated cost per cluster (including terminated ones)."""
    from skypilot_tpu import core
    rows = [[
        r['name'],
        str(r['resources']),
        _fmt_duration(r['duration_seconds']),
        f'${r["cost"]:.2f}',
    ] for r in core.cost_report()]
    if not rows:
        click.echo('No usage recorded.')
        return
    click.echo(_table(['NAME', 'RESOURCES', 'DURATION', 'COST'], rows))


@cli.command()
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--minimize', type=click.Choice(['cost', 'time']),
              default='cost')
@_resource_flags
def optimize(entrypoint, minimize, name, workdir, cloud, tpus, cpus,
             memory, use_spot, region, zone, num_nodes, env):
    """Show the placement plan for a task without launching it."""
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu import optimizer
    task = _make_task(entrypoint, name, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    with dag_lib.Dag() as dag:
        dag.add(task)
    optimizer.optimize(
        dag, minimize=optimizer.OptimizeTarget(minimize))


# ------------------------------------------------------------------ storage


@cli.group(cls=_NaturalOrderGroup)
def storage():
    """Manage framework-created buckets."""


@storage.command('ls')
def storage_ls():
    from skypilot_tpu import core
    rows = []
    for s in core.storage_ls():
        # Source/mode/store live inside the pickled handle, not as
        # flat row columns.
        h = s['handle']
        mode = getattr(h, 'mode', None)
        source = getattr(h, 'source', None)
        if isinstance(source, list):
            source = ','.join(source)
        rows.append([
            s['name'],
            source or '-',
            getattr(h, 'store', 'gcs'),
            getattr(mode, 'value', str(mode)),
            s['status'].value,
            _fmt_ts(s.get('launched_at')),
        ])
    if not rows:
        click.echo('No storage.')
        return
    click.echo(_table(['NAME', 'SOURCE', 'STORE', 'MODE', 'STATUS',
                       'CREATED'], rows))


@storage.command('delete')
@click.argument('names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def storage_delete(names, yes):
    from skypilot_tpu import core
    for n in names:
        if not yes:
            click.confirm(f'Delete storage {n!r}?', default=True,
                          abort=True)
        core.storage_delete(n)
        click.echo(f'Storage {n!r} deleted.')


@cli.command()
@click.argument('shell', type=click.Choice(['bash', 'zsh', 'fish']))
def completion(shell):
    """Print the shell-completion script (parity: sky/cli.py:305-460).

    Install:  eval "$(skytpu completion bash)"   (or zsh/fish)
    """
    from click.shell_completion import get_completion_class
    comp_cls = get_completion_class(shell)
    if comp_cls is None:
        raise click.UsageError(f'no completion support for {shell!r}')
    comp = comp_cls(cli, {}, 'skytpu', '_SKYTPU_COMPLETE')
    click.echo(comp.source())


@cli.group(cls=_NaturalOrderGroup)
def data():
    """Token-corpus tooling (data/loader.py)."""


@data.command('tokenize')
@click.argument('text_path')
@click.argument('out_path')
@click.option('--tokenizer', '-t', required=True,
              help='HF tokenizer (name, local dir, or cached id).')
@click.option('--no-eos', is_flag=True, default=False,
              help="Don't append the tokenizer's EOS token.")
def data_tokenize(text_path, out_path, tokenizer, no_eos):
    """Tokenize a UTF-8 text file into a memmap-able token file."""
    from skypilot_tpu.data import loader
    n = loader.tokenize_text_file(text_path, out_path, tokenizer,
                                  append_eos=not no_eos)
    click.echo(f'{out_path}: {n} tokens')


@data.command('inspect')
@click.argument('path')
def data_inspect(path):
    """Token count / dtype / sequence capacity of a token file."""
    from skypilot_tpu.data import loader
    ds = loader.TokenDataset(path)
    click.echo(f'{path}: {len(ds)} tokens, dtype {ds.tokens.dtype}')
    for seq in (1024, 2048, 4096, 8192):
        click.echo(f'  seq {seq}: {ds.num_sequences(seq)} sequences')


# --------------------------------------------------------------- jobs group


@cli.group(cls=_NaturalOrderGroup)
def jobs():
    """Managed jobs with automatic preemption recovery."""


@jobs.command('launch')
@click.argument('entrypoint', nargs=-1, required=True)
@_resource_flags
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_launch(entrypoint, name, workdir, cloud, tpus, cpus, memory,
                use_spot, region, zone, num_nodes, env, detach_run, yes):
    """Launch a managed job (controller supervises + recovers it)."""
    from skypilot_tpu import jobs as jobs_lib
    task = _make_task(entrypoint, name, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    if not yes:
        click.confirm(f'Launch managed job {task.name or "(unnamed)"!r}?',
                      default=True, abort=True)
    job_id = jobs_lib.launch(task, name=name, detach_run=detach_run)
    click.echo(f'Managed job submitted: {job_id}')


@jobs.command('queue')
@click.option('--refresh', '-r', is_flag=True, default=False)
def jobs_queue(refresh):
    """Show all managed jobs."""
    from skypilot_tpu import jobs as jobs_lib
    from skypilot_tpu.jobs import utils as jobs_utils
    rows = jobs_lib.queue(refresh=refresh)
    if not rows:
        click.echo('No managed jobs.')
        return
    click.echo(jobs_utils.format_job_queue(rows))


@jobs.command('cancel')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--name', '-n', default=None)
@click.option('--all', 'all_jobs', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_cancel(job_ids, name, all_jobs, yes):
    from skypilot_tpu import jobs as jobs_lib
    if not job_ids and name is None and not all_jobs:
        raise click.UsageError('Provide JOB_IDS, --name, or --all.')
    if not yes:
        click.confirm('Cancel managed job(s)?', default=True, abort=True)
    cancelled = jobs_lib.cancel(job_ids=list(job_ids) or None, name=name,
                                all_jobs=all_jobs)
    click.echo(f'Cancelled: {cancelled or "none"}')


@jobs.command('logs')
@click.argument('job_id', type=int, required=False, default=None)
@click.option('--name', '-n', default=None)
@click.option('--no-follow', is_flag=True, default=False)
def jobs_logs(job_id, name, no_follow):
    from skypilot_tpu import jobs as jobs_lib
    raise SystemExit(
        jobs_lib.tail_logs(name=name, job_id=job_id,
                           follow=not no_follow))


@jobs.command('dashboard')
@click.option('--port', '-p', type=int, default=8765)
@click.option('--host', default='127.0.0.1')
def jobs_dashboard(port, host):
    """Serve an HTML dashboard of the managed jobs queue."""
    from skypilot_tpu.jobs import dashboard
    try:  # bind BEFORE announcing a URL
        server, thread = dashboard.start_dashboard(host=host, port=port,
                                                   background=True)
    except OSError as e:
        raise click.ClickException(f'cannot bind {host}:{port}: {e}')
    bound = server.server_address[1]
    click.echo(f'Dashboard: http://{host}:{bound}/ (Ctrl-C to stop)')
    try:
        thread.join()
    except KeyboardInterrupt:
        server.shutdown()


# -------------------------------------------------------------- serve group


@cli.group(cls=_NaturalOrderGroup)
def serve():
    """Autoscaled serving with HTTP load balancing."""


@serve.command('up')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--service-name', '-n', default=None)
@_resource_flags(include_name=False)
@click.option('--lb-policy', default=None,
              type=click.Choice(['round_robin', 'least_load',
                                 'prefix_affinity']),
              help='Load-balancing policy (overrides the service '
                   'spec). prefix_affinity routes prompts sharing a '
                   'leading token-block prefix to the same replica so '
                   'the fleet approximates one radix prefix cache.')
@click.option('--qos-policy', default=None,
              type=click.Choice(['off', 'tenant_rate']),
              help='LB-edge QoS (overrides the service spec): '
                   'tenant_rate enforces per-tenant token-bucket rate '
                   'limits at the load balancer (SKYTPU_SERVE_QOS_* '
                   'knobs set the rates); over-rate tenants get a '
                   'typed 429 + Retry-After.')
@click.option('--slo-ttft-ms', default=None, type=float,
              help='Autoscale to a latency SLO instead of QPS: keep '
                   'the fleet\'s worst per-replica TTFT p95 under this '
                   'many milliseconds (requires max_replicas in the '
                   'service spec; mutually exclusive with '
                   'target_qps_per_replica).')
@click.option('--tp-size', default=None, type=int,
              help='Tensor-parallel degree per replica (overrides '
                   'resources.tp_size in the YAML): each replica '
                   'head-shards its KV cache over this many chips, '
                   'multiplying per-replica KV capacity by the same '
                   'factor. TP and single-chip replicas coexist behind '
                   'the same load balancer.')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_up(entrypoint, service_name, workdir, cloud, tpus, cpus,
             memory, use_spot, region, zone, num_nodes, env, lb_policy,
             qos_policy, slo_ttft_ms, tp_size, yes):
    """Bring up a service from a task YAML with a `service:` section."""
    import dataclasses as _dc
    from skypilot_tpu import serve as serve_lib
    task = _make_task(entrypoint, None, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    if tp_size is not None:
        task.set_resources(
            [r.copy(tp_size=tp_size) for r in task.resources])
    if (qos_policy is not None or slo_ttft_ms is not None) and \
            task.service is None:
        raise click.UsageError(
            '--qos-policy/--slo-ttft-ms require a task with a '
            '`service:` section')
    if qos_policy is not None or slo_ttft_ms is not None:
        # dataclasses.replace re-runs spec validation (e.g. slo_ttft_ms
        # requires max_replicas) before anything launches.
        overrides = {}
        if qos_policy is not None:
            overrides['qos_policy'] = qos_policy
        if slo_ttft_ms is not None:
            overrides['slo_ttft_ms'] = slo_ttft_ms
        task.service = _dc.replace(task.service, **overrides)
    if not yes:
        click.confirm(f'Bring up service {service_name or task.name!r}?',
                      default=True, abort=True)
    svc_name, endpoint = serve_lib.up(task, service_name,
                                      policy=lb_policy)
    click.echo(f'Service {svc_name!r} is initializing; endpoint: '
               f'{endpoint}')


@serve.command('status')
def serve_status():
    from skypilot_tpu import serve as serve_lib
    from skypilot_tpu.serve import serve_utils
    services = serve_lib.status()
    if not services:
        click.echo('No services.')
        return
    click.echo(serve_utils.format_service_table(services))


@serve.command('update')
@click.argument('service_name')
@click.argument('entrypoint', nargs=-1, required=True)
@_resource_flags(include_name=False)
@click.option('--mode', type=click.Choice(['rolling', 'blue_green']),
              default='rolling', show_default=True,
              help='rolling: bounded surge of one; blue_green: full new '
                   'fleet reaches READY before old replicas drain.')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_update(service_name, entrypoint, workdir, cloud, tpus,
                 cpus, memory, use_spot, region, zone, num_nodes, env,
                 mode, yes):
    """Update a service to a new task/spec (rolling or blue-green)."""
    from skypilot_tpu import serve as serve_lib
    task = _make_task(entrypoint, None, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    if not yes:
        click.confirm(f'Update service {service_name!r}?', default=True,
                      abort=True)
    version = serve_lib.update(task, service_name, mode=mode)
    click.echo(f'Service {service_name!r} updating ({mode}) to version '
               f'{version}.')


@serve.command('terminate-replica')
@click.argument('service_name')
@click.argument('replica_id', type=int)
@click.option('--purge', is_flag=True, default=False,
              help='Drop the replica record instead of keeping it '
                   'visible in `serve status`.')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_terminate_replica(service_name, replica_id, purge, yes):
    """Tear down one replica of a service (parity: sky serve
    terminate-replica, sky/serve/core.py:507)."""
    from skypilot_tpu import serve as serve_lib
    if not yes:
        click.confirm(
            f'Terminate replica {replica_id} of {service_name!r}?',
            default=True, abort=True)
    serve_lib.terminate_replica(service_name, replica_id, purge=purge)
    click.echo(f'Replica {replica_id} of {service_name!r} is '
               'terminating.')


@serve.command('down')
@click.argument('service_names', nargs=-1)
@click.option('--all', 'all_services', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_down(service_names, all_services, purge, yes):
    from skypilot_tpu import serve as serve_lib
    if not service_names and not all_services:
        raise click.UsageError('Provide SERVICE_NAMES or --all.')
    if not yes:
        what = 'ALL services' if all_services else ', '.join(service_names)
        click.confirm(f'Terminate {what}?', default=True, abort=True)
    terminated = serve_lib.down(list(service_names) or None,
                                all_services=all_services, purge=purge)
    click.echo(f'Terminating: {", ".join(terminated) or "none"}')


@serve.command('logs')
@click.argument('service_name')
@click.option('--replica-id', type=int, default=None,
              help='Stream one replica instead of the controller.')
@click.option('--no-follow', is_flag=True, default=False)
def serve_logs(service_name, replica_id, no_follow):
    from skypilot_tpu import serve as serve_lib
    raise SystemExit(
        serve_lib.tail_logs(service_name, replica_id=replica_id,
                            follow=not no_follow))


# -------------------------------------------------------------- bench group


@cli.group(cls=_NaturalOrderGroup)
def bench():
    """Cost benchmarks: one task on N candidate resources, compare $/step.
    Parity: `sky bench` (sky/cli.py:4615)."""


@bench.command('launch')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--benchmark', '-b', required=True, help='Benchmark name.')
@click.option('--candidate', '-C', 'candidates', multiple=True,
              help='Candidate accelerator (repeat), e.g. -C tpu-v5e-8 '
                   '-C tpu-v5e-16. Defaults to the task\'s own resources.')
@_resource_flags
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_launch(entrypoint, benchmark, candidates, name, workdir, cloud,
                 tpus, cpus, memory, use_spot, region, zone, num_nodes, env,
                 yes):
    """Launch ENTRYPOINT on each candidate resource in parallel."""
    from skypilot_tpu import bench as bench_lib
    task = _make_task(entrypoint, name, workdir, cloud, tpus, cpus, memory,
                      use_spot, region, zone, num_nodes, env)
    base_set = list(task.resources)
    if candidates:
        if len(base_set) > 1:
            raise click.UsageError(
                'Cannot combine -C candidates with a task YAML declaring '
                'multiple resources alternatives: the candidate base would '
                'be ambiguous.')
        resources_list = [base_set[0].copy(accelerator=c)
                          for c in candidates]
    else:
        # No -C: every YAML alternative IS a candidate.
        resources_list = base_set
    if not yes:
        click.confirm(
            f'Launching benchmark {benchmark!r} on {len(resources_list)} '
            f'candidate cluster(s). Proceed?', default=True, abort=True)
    launched = bench_lib.launch_benchmark(benchmark, task, resources_list)
    click.echo(f'Benchmark {benchmark!r}: launched {len(launched)} '
               f'cluster(s): {", ".join(launched)}')
    click.echo(f'Track with: skytpu bench show {benchmark}')


@bench.command('ls')
def bench_ls():
    """List benchmarks."""
    from skypilot_tpu.bench import state as bench_state
    rows = [[b['name'], b['task_name'] or '-', _fmt_ts(b['launched_at']),
             b['status']] for b in bench_state.get_benchmarks()]
    click.echo(_table(['BENCHMARK', 'TASK', 'LAUNCHED', 'STATUS'], rows)
               if rows else 'No benchmarks.')


@bench.command('show')
@click.argument('benchmark')
def bench_show(benchmark):
    """Refresh and show one benchmark's candidate results."""
    from skypilot_tpu import bench as bench_lib
    from skypilot_tpu.bench import state as bench_state
    if bench_state.get_benchmark(benchmark) is None:
        raise click.UsageError(f'Benchmark {benchmark!r} not found.')
    rows = bench_lib.update_benchmark_state(benchmark)

    def _f(x, fmt='{:.3f}'):
        return fmt.format(x) if x is not None else '-'

    table_rows = []
    for r in rows:
        table_rows.append([
            r['cluster'], str(r['resources']), r['status'],
            r['num_steps'] if r['num_steps'] is not None else '-',
            _f(r['seconds_per_step']),
            _f(r['init_seconds'], '{:.1f}'),
            _fmt_duration(r['estimated_total_seconds']),
            _f(r['estimated_cost'], '${:.2f}'),
        ])
    click.echo(_table(['CLUSTER', 'RESOURCES', 'STATUS', 'STEPS', 'S/STEP',
                       'INIT(S)', 'EST.TOTAL', 'EST.COST'], table_rows))


@bench.command('down')
@click.argument('benchmark')
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_down(benchmark, yes):
    """Terminate all of a benchmark's candidate clusters."""
    from skypilot_tpu import bench as bench_lib
    from skypilot_tpu.bench import state as bench_state
    if bench_state.get_benchmark(benchmark) is None:
        raise click.UsageError(f'Benchmark {benchmark!r} not found.')
    if not yes:
        click.confirm(f'Terminate all clusters of benchmark {benchmark!r}?',
                      default=True, abort=True)
    bench_lib.down_benchmark_clusters(benchmark)
    click.echo(f'Benchmark {benchmark!r} clusters terminated.')


@bench.command('delete')
@click.argument('benchmark')
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_delete(benchmark, yes):
    """Delete a benchmark's records (does not touch clusters)."""
    from skypilot_tpu import bench as bench_lib
    if not yes:
        click.confirm(f'Delete benchmark {benchmark!r} records?',
                      default=True, abort=True)
    bench_lib.delete_benchmark(benchmark)
    click.echo(f'Benchmark {benchmark!r} deleted.')




_INFER_PROFILES = {
    # Measured operating points for a 7B-class model on one v5e chip
    # (docs/performance.md).  TPOT at decode window K is s + F/K with
    # F the per-dispatch fixed cost (~108 ms through the tunnel) and s
    # the marginal step (~16 ms) — scripts/bench_decode_micro.py — so
    # the latency preset runs a 16-step window PLUS the queue-aware
    # adaptive window (full K while nothing waits; K=2 only when an
    # arrival is queued with a free slot).  Same-chip A/B at 32 slots:
    # single-stream TPOT 53 -> 33 ms, qps-1.0 TPOT p50 104 -> 45 ms,
    # TTFT p50 1.4 -> 0.52 s, 143 -> 184 tok/s.  (r4's occupancy-based
    # adaptive window LOST on the tunnel — short windows whenever few
    # slots were busy — and was left opt-in; the queue-aware policy
    # replaced it.)  throughput keeps the widest window and batch.
    'latency': {'num_slots': 32, 'decode_steps': 16,
                'prefills_per_gap': 2, 'adaptive_window': True,
                'decode_lookahead': True},
    'throughput': {'num_slots': 48, 'decode_steps': 32,
                   'prefills_per_gap': 4},
}


def _apply_infer_profile(ctx, profile, values):
    """Profile presets fill any knob the user did NOT set explicitly."""
    if not profile:
        return values
    import click.core as _cc
    out = dict(values)
    for key, preset in _INFER_PROFILES[profile].items():
        if key not in out:
            continue
        src = ctx.get_parameter_source(key)
        if src == _cc.ParameterSource.DEFAULT:
            out[key] = preset
    return out

# -------------------------------------------------------------- infer group


@cli.group()
def infer():
    """Run the built-in inference engine (JetStream-analog)."""


@infer.command('serve')
@click.option('--model', default='llama-1b', help='Registered model name.')
@click.option('--port', default=8100, type=int)
@click.option('--host', default='0.0.0.0')
@click.option('--num-slots', default=8, type=int,
              help='Concurrent decode slots (continuous batching width).')
@click.option('--max-cache-len', default=2048, type=int)
@click.option('--tokenizer', default=None, help='HF tokenizer (optional).')
@click.option('--eos-id', default=None, type=int,
              help='Stop token (defaults to the tokenizer\'s EOS).')
@click.option('--decode-steps', default=8, type=int,
              help='Decode tokens per device dispatch (latency knob).')
@click.option('--hf-model', default=None,
              help='HF Llama checkpoint (local path or warm cache): serve '
                   'real pretrained weights; implies its tokenizer.')
@click.option('--cache-dtype', default='bfloat16',
              type=click.Choice(['bfloat16', 'fp8']),
              help='KV-cache storage dtype. fp8 (e4m3) halves cache HBM '
                   'per slot (~+9% decode throughput at equal slots); '
                   'minor quality loss possible.')
@click.option('--tensor-parallel', default=0, type=int,
              help='Shard the model over N local chips (TP serving).')
@click.option('--weight-dtype', default='bf16',
              type=click.Choice(['bf16', 'int8']),
              help='Weight storage. int8 halves weight HBM (a 7B fits '
                   'one 16 GB chip) and speeds weight-streaming-bound '
                   'decode; per-channel scales keep logits close.')
@click.option('--profile', default=None,
              type=click.Choice(sorted(_INFER_PROFILES)),
              help='Preset operating point (docs/performance.md); '
                   'explicit flags win over the preset.')
@click.option('--prefills-per-gap', type=int, default=4,
              help='Max prefills between decode windows '
                   '(latency/throughput knob).')
@click.option('--platform', default=None,
              type=click.Choice(['cpu', 'tpu']),
              help='Pin jax onto this platform (CPU replicas for dev '
                   'serving / hermetic CI; default = jax\'s pick).')
@click.option('--max-ttft', type=float, default=None,
              help='Admission bound (s): shed requests (HTTP 429 + '
                   'Retry-After) while recent observed TTFT exceeds '
                   'this instead of queueing unboundedly. Default: off.')
@click.option('--max-queue', type=int, default=None,
              help='Hard first-token backlog cap: shed (429) the moment '
                   'this many requests are queued ahead (bounds the '
                   'TTFT tail feedforward). Default: off.')
@click.option('--draft-len', type=int, default=0,
              help='Speculative decoding: prompt-lookup draft tokens '
                   'verified per dispatch (greedy requests). Wins on '
                   'input-grounded output; 0 disables.')
@click.option('--ngram-max', type=int, default=4,
              help='Longest n-gram tried when drafting (--draft-len).')
@click.option('--max-prefixes', type=int, default=16,
              help='Resident prefix-KV entries for POST /cache_prefix '
                   '(LRU-evicted; 0 disables prefix caching).')
@click.option('--lora-rank', type=int, default=0,
              help='Multi-LoRA serving: build the model with stacked '
                   'rank-R adapters (POST /load_adapter to register; '
                   '0 disables).')
@click.option('--lora-max-adapters', type=int, default=8,
              help='Resident adapter slots (--lora-rank).')
@click.option('--adapter-dir', default=None,
              help='Directory POST /load_adapter may read adapters '
                   'from. Unset: runtime adapter loading is disabled '
                   '(the API is unauthenticated; an open path would '
                   'let any client probe the filesystem).')
@click.option('--adaptive-window/--no-adaptive-window', default=False,
              help='Queue-aware decode windows: full decode_steps '
                   'while nothing is waiting (TPOT-optimal — the '
                   'per-dispatch fixed cost amortizes over the whole '
                   'window), short 2-step dispatches only while an '
                   'arrival is queued with a free slot (TTFT-optimal).'
                   '  On by default under --profile latency; '
                   '--no-adaptive-window turns it off explicitly.')
@click.option('--decode-lookahead/--no-decode-lookahead', default=False,
              help='Dispatch the next decode window from device-side '
                   'state before reading the current one: steady-state '
                   'decode pays max(round-trip, compute) per window '
                   'instead of their sum.  Skipped automatically while '
                   'arrivals wait (TTFT) and under --draft-len.  On by '
                   'default under --profile latency.')
@click.option('--auto-prefix', is_flag=True, default=False,
              help='Automatic prefix caching: a prompt head seen '
                   'twice registers itself as a resident prefix '
                   '(bucket-quantized lengths; vLLM-APC analog). '
                   'Explicit POST /cache_prefix always works.')
@click.option('--qos', is_flag=True, default=False,
              help='QoS admission: per-tenant weighted-fair queueing '
                   '(tenant_id field), strict interactive>batch '
                   'priority with preemption at chunked-prefill '
                   'boundaries, and deadline-driven shedding of work '
                   'projected to miss its deadline_s.')
@click.option('--qos-tenant-weights', default=None,
              help='WFQ tenant weights, e.g. "teamA=3,teamB=1" '
                   '(unlisted tenants weigh 1.0; needs --qos).')
@click.pass_context
def infer_serve(ctx, model, port, host, num_slots, max_cache_len,
                tokenizer, eos_id, decode_steps, hf_model, cache_dtype,
                tensor_parallel, weight_dtype, profile,
                prefills_per_gap, platform, max_ttft, max_queue,
                draft_len, ngram_max, max_prefixes, lora_rank,
                lora_max_adapters, adapter_dir, adaptive_window,
                decode_lookahead, auto_prefix, qos, qos_tenant_weights):
    """Start the HTTP inference server on this host."""
    from skypilot_tpu.infer import server as infer_server
    knobs = _apply_infer_profile(ctx, profile, {
        'num_slots': num_slots, 'decode_steps': decode_steps,
        'prefills_per_gap': prefills_per_gap,
        'adaptive_window': adaptive_window,
        'decode_lookahead': decode_lookahead})
    num_slots, decode_steps = knobs['num_slots'], knobs['decode_steps']
    prefills_per_gap = knobs['prefills_per_gap']
    adaptive_window = knobs['adaptive_window']
    decode_lookahead = knobs['decode_lookahead']
    click.echo(f'serving {hf_model or model} on {host}:{port}')
    infer_server.run(model=model, host=host, port=port,
                     num_slots=num_slots, max_cache_len=max_cache_len,
                     tokenizer_name=tokenizer, eos_id=eos_id,
                     decode_steps=decode_steps, hf_model=hf_model,
                     cache_dtype=cache_dtype,
                     tensor_parallel=tensor_parallel,
                     weight_dtype=weight_dtype,
                     prefills_per_gap=prefills_per_gap,
                     platform=platform, max_ttft=max_ttft,
                     max_queue=max_queue, draft_len=draft_len,
                     ngram_max=ngram_max, max_prefixes=max_prefixes,
                     lora_rank=lora_rank,
                     lora_max_adapters=lora_max_adapters,
                     adapter_dir=adapter_dir,
                     adaptive_window=adaptive_window,
                     decode_lookahead=decode_lookahead,
                     auto_prefix=auto_prefix, qos=qos,
                     qos_tenant_weights=qos_tenant_weights)


@infer.command('bench')
@click.option('--model', default='llama-1b')
@click.option('--num-requests', default=32, type=int)
@click.option('--prompt-len', default=128, type=int)
@click.option('--new-tokens', default=64, type=int)
@click.option('--num-slots', default=8, type=int)
@click.option('--max-cache-len', default=2048, type=int)
@click.option('--decode-steps', default=8, type=int)
@click.option('--cache-dtype', default='bfloat16',
              type=click.Choice(['bfloat16', 'fp8']),
              help='KV-cache storage dtype. fp8 (e4m3) halves cache HBM '
                   'per slot (~+9% decode throughput at equal slots); '
                   'minor quality loss possible.')
@click.option('--weight-dtype', default='bf16',
              type=click.Choice(['bf16', 'int8']),
              help='Weight storage (see infer serve --weight-dtype).')
@click.option('--serving', is_flag=True, default=False,
              help='Serving mode: requests arrive over time into the '
                   'continuous-batching loop; TTFT/TPOT are real '
                   'under-load latencies (vs offline batch).')
@click.option('--qps', type=float, default=None,
              help='Poisson arrival rate for --serving (default: all '
                   'at once).')
@click.option('--prefills-per-gap', type=int, default=4,
              help='Serving: max prefills between decode windows '
                   '(latency/throughput knob).')
@click.option('--profile', default=None,
              type=click.Choice(sorted(_INFER_PROFILES)),
              help='Preset operating point (docs/performance.md); '
                   'explicit flags win over the preset.')
@click.option('--draft-len', type=int, default=0,
              help='Speculative decoding: prompt-lookup draft tokens '
                   'verified per dispatch (0 disables). The metrics '
                   'line gains spec_* acceptance counters.')
@click.option('--ngram-max', type=int, default=4,
              help='Longest n-gram tried when drafting (--draft-len).')
@click.option('--adaptive-window/--no-adaptive-window', default=False,
              help='Queue-aware decode windows (see infer serve).')
@click.option('--decode-lookahead/--no-decode-lookahead', default=False,
              help='RTT-hiding lookahead dispatch (see infer serve).')
@click.pass_context
def infer_bench(ctx, model, num_requests, prompt_len, new_tokens,
                num_slots, max_cache_len, decode_steps, cache_dtype,
                weight_dtype, serving, qps, prefills_per_gap, profile,
                draft_len, ngram_max, adaptive_window, decode_lookahead):
    """Benchmark the engine (req/s, tok/s, TTFT) with synthetic prompts."""
    import dataclasses as _dc
    import json as json_lib

    from skypilot_tpu.infer import (InferConfig, InferenceEngine,
                                    resolve_cache_dtype)
    from skypilot_tpu.models import get_model_config
    knobs = _apply_infer_profile(ctx, profile, {
        'num_slots': num_slots, 'decode_steps': decode_steps,
        'prefills_per_gap': prefills_per_gap,
        'adaptive_window': adaptive_window,
        'decode_lookahead': decode_lookahead})
    num_slots = knobs['num_slots']
    decode_steps = knobs['decode_steps']
    prefills_per_gap = knobs['prefills_per_gap']
    cfg = InferConfig(model=model, num_slots=num_slots,
                      max_cache_len=max_cache_len,
                      decode_steps=decode_steps,
                      prefills_per_gap=prefills_per_gap,
                      cache_dtype=resolve_cache_dtype(cache_dtype),
                      draft_len=draft_len, ngram_max=ngram_max,
                      adaptive_decode_window=knobs['adaptive_window'],
                      decode_lookahead=knobs['decode_lookahead'])
    model_config = get_model_config(model)
    if weight_dtype != 'bf16':
        from skypilot_tpu.models.llama import LlamaConfig
        if not isinstance(model_config, LlamaConfig):
            raise click.UsageError(
                '--weight-dtype int8 currently supports the llama '
                f'family; got {type(model_config).__name__}')
        model_config = _dc.replace(model_config, weight_dtype=weight_dtype)
    engine = InferenceEngine(model_config, cfg)
    if serving:
        metrics = engine.benchmark_serving(num_requests=num_requests,
                                           prompt_len=prompt_len,
                                           new_tokens=new_tokens, qps=qps)
    else:
        metrics = engine.benchmark(num_requests=num_requests,
                                   prompt_len=prompt_len,
                                   new_tokens=new_tokens)
    if draft_len:
        metrics.update({f'spec_{k}': v
                        for k, v in engine.spec_stats.items()})
    click.echo(json_lib.dumps(metrics))


def main() -> None:
    try:
        cli.main(standalone_mode=True)
    except exceptions.SkyTpuError as e:
        raise SystemExit(f'skytpu: {e}') from e


if __name__ == '__main__':
    main()
