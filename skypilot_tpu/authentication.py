"""SSH key management: one framework keypair, injected per cloud.

Parity: sky/authentication.py — generates ``~/.skytpu/keys/skytpu-key``
once; the public key is injected into TPU-VM / GCE instance metadata at
provision time so the client can SSH without gcloud.
"""
import os
import subprocess
from typing import Tuple

import filelock

from skypilot_tpu import logsys
from skypilot_tpu.utils import common

logger = logsys.init_logger(__name__)

PRIVATE_KEY_NAME = 'skytpu-key'


def get_key_paths() -> Tuple[str, str]:
    d = common.keys_dir()
    return (os.path.join(d, PRIVATE_KEY_NAME),
            os.path.join(d, PRIVATE_KEY_NAME + '.pub'))


def get_or_generate_keys() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_path), generating once."""
    private, public = get_key_paths()
    lock = filelock.FileLock(private + '.lock')
    with lock:
        if not (os.path.exists(private) and os.path.exists(public)):
            common.ensure_dir(os.path.dirname(private))
            subprocess.run(
                ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f', private,
                 '-C', f'skytpu-{common.get_user_hash()}'],
                check=True)
            os.chmod(private, 0o600)
    return private, public


def public_key_openssh() -> str:
    _, public = get_or_generate_keys()
    with open(public, 'r', encoding='utf-8') as f:
        return f.read().strip()


def default_ssh_user() -> str:
    # TPU VMs accept any user present in the injected ssh-keys metadata.
    return 'skytpu'
