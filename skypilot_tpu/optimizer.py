"""Placement optimizer: pick (cloud, slice/VM, region, zone, spot) per task.

Parity: sky/optimizer.py — enumerate feasible "launchables" per task with
$/hr from the catalog, then minimize cost or end-to-end time over the DAG
(chain DAGs via DP, sky/optimizer.py:409; general DAGs via ILP, :470).

TPU-first differences:
- Candidates are zone-granular (TPU capacity and stockouts are per-zone),
  and the *ranked candidate list* is kept on each task for the failover
  provisioner to walk (stockout is the dominant failure mode).
- The TIME objective uses a simple roofline: estimated task duration scales
  inversely with the slice's aggregate bf16 TFLOPs, so "minimize time"
  naturally prefers bigger/faster slices while "minimize cost" prefers
  cheaper ones.
- The general-DAG solver is an exact branch-and-bound over the (small) TPU
  catalog instead of an external pulp/CBC dependency.
"""
import enum
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import exceptions, logsys
from skypilot_tpu.clouds import Cloud
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import timeline, ux

logger = logsys.init_logger(__name__)

_DEFAULT_DURATION_HOURS = 1.0
# Reference slice for duration scaling: a v5e-8 (8 x 196.8 TFLOPs).
_REFERENCE_TFLOPS = 8 * 196.8


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


class Candidate:
    """One concrete placement choice with its estimated cost/time."""

    __slots__ = ('resources', 'region', 'zone', 'cost_per_hour',
                 'duration_hours')

    def __init__(self, resources: Resources, region: str, zone: Optional[str],
                 cost_per_hour: float, duration_hours: float):
        self.resources = resources
        self.region = region
        self.zone = zone
        self.cost_per_hour = cost_per_hour
        self.duration_hours = duration_hours

    @property
    def total_cost(self) -> float:
        return self.cost_per_hour * self.duration_hours

    def __repr__(self):
        return (f'<Candidate {self.resources.pretty()} {self.zone} '
                f'${self.cost_per_hour:.2f}/hr {self.duration_hours:.2f}h>')


def _estimate_duration_hours(task, resources: Resources) -> float:
    """Roofline duration estimate (parity role:
    _estimate_nodes_cost_or_time, sky/optimizer.py:239)."""
    base = task.estimated_duration_hours or _DEFAULT_DURATION_HOURS
    info = resources.slice_info
    if info is None:
        return base
    # Sublinear speedup (communication overhead grows with slice size):
    # speedup = (relative TFLOPs)^0.9.  This makes "minimize time" prefer
    # bigger slices while "minimize cost" prefers smaller/cheaper ones.
    rel = max(info.total_tflops_bf16, 1e-9) / _REFERENCE_TFLOPS
    return base / (rel ** 0.9)


def _enumerate_candidates(task, blocked: Optional[List[Resources]]
                          ) -> List[Candidate]:
    """All feasible (resources, region, zone) placements for one task."""
    enabled = check_lib.get_cached_enabled_clouds_or_refresh()
    blocked = blocked or []
    out: List[Candidate] = []
    for want in task.resources:
        clouds = ([Cloud.from_name(want.cloud)]
                  if want.cloud is not None else
                  [Cloud.from_name(name) for name in enabled])
        for cloud in clouds:
            if cloud is None or cloud.NAME not in enabled:
                continue
            for feasible in cloud.get_feasible_resources(want):
                for region, zone in cloud.region_zones_for(feasible):
                    pinned = feasible.copy(region=region, zone=zone)
                    if any(pinned.should_be_blocked_by(b) for b in blocked):
                        continue
                    try:
                        cost = cloud.hourly_cost(pinned) * task.num_nodes
                    except exceptions.ResourcesUnavailableError:
                        continue
                    out.append(
                        Candidate(pinned, region, zone, cost,
                                  _estimate_duration_hours(task, pinned)))
    return out


def _rank(candidates: List[Candidate],
          minimize: OptimizeTarget) -> List[Candidate]:
    if minimize == OptimizeTarget.COST:
        return sorted(candidates,
                      key=lambda c: (c.total_cost, c.duration_hours))
    return sorted(candidates, key=lambda c: (c.duration_hours, c.total_cost))


@timeline.event
def optimize(dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocked_resources: Optional[List[Resources]] = None,
             quiet: bool = False):
    """Assign ``task.best_resources`` (and ranked ``task.candidates``) for
    every task in the DAG.  Returns the same DAG.

    Raises ResourcesUnavailableError if any task has no feasible placement.
    """
    per_task: Dict[object, List[Candidate]] = {}
    for task in dag.tasks:
        cands = _enumerate_candidates(task, blocked_resources)
        if not cands:
            raise exceptions.ResourcesUnavailableError(
                f'No feasible placement for task {task.name or task!r}. '
                f'Requested: '
                f'{[r.pretty() for r in task.resources]}. Check `skytpu '
                f'check` and the catalog (`skytpu show-tpus`).')
        per_task[task] = _rank(cands, minimize)

    if len(dag.tasks) <= 1 or dag.is_chain():
        choice = _optimize_chain_dp(dag, per_task, minimize)
    else:
        choice = _optimize_general_bb(dag, per_task, minimize)

    for task, cand in choice.items():
        ranked = per_task[task]
        # Failover order: chosen candidate first, then remaining by rank.
        task.candidates = [cand] + [c for c in ranked if c is not cand]
        task.best_resources = cand.resources
    if not quiet:
        _print_plan(dag, choice, minimize)
    return dag


# GCP inter-region egress: $/GB (catalog snapshot rate) and an
# effective transfer bandwidth for the TIME objective (bucket-to-bucket
# inter-region copies sustain roughly 1 GB/s in practice).
_EGRESS_DOLLARS_PER_GB = 0.12
_EGRESS_GB_PER_HOUR = 3600.0


def _egress_cost(src: Candidate, dst: Candidate,
                 gb: Optional[float] = None,
                 minimize: 'OptimizeTarget' = None) -> float:
    """Cross-placement egress between consecutive DAG tasks (parity:
    sky/optimizer.py:239's cost/time model) in the OBJECTIVE's unit:
    dollars for COST, transfer HOURS for TIME — adding $/GB to an
    hours objective would let a declared 500 GB output read as a
    500-hour penalty.

    `gb` is the upstream task's declared `estimated_outputs_gb`:
    None (undeclared) falls back to a 1 GB floor so cross-region hops
    still carry a small co-location penalty; an EXPLICIT 0 declares
    "no outputs" and disables the penalty entirely."""
    if src.region == dst.region:
        return 0.0
    gb = 1.0 if gb is None else max(float(gb), 0.0)
    if minimize == OptimizeTarget.TIME:
        return gb / _EGRESS_GB_PER_HOUR
    return _EGRESS_DOLLARS_PER_GB * gb


def _objective(cand: Candidate, minimize: OptimizeTarget) -> float:
    return (cand.total_cost
            if minimize == OptimizeTarget.COST else cand.duration_hours)


def _optimize_chain_dp(dag, per_task, minimize) -> Dict[object, Candidate]:
    """Exact forward DP over a linear chain with pairwise egress costs
    (parity: sky/optimizer.py:409)."""
    order = dag.topological_order()
    layers: List[List[Candidate]] = [per_task[t] for t in order]
    costs: List[Dict[int, float]] = [{}]
    parents: List[Dict[int, int]] = [{}]
    for j, cand in enumerate(layers[0]):
        costs[0][j] = _objective(cand, minimize)
    for i in range(1, len(layers)):
        costs.append({})
        parents.append({})
        for j, cand in enumerate(layers[i]):
            best, arg = float('inf'), -1
            up_gb = getattr(order[i - 1], 'estimated_outputs_gb', None)
            for pj, pval in costs[i - 1].items():
                val = pval + _objective(cand, minimize) + _egress_cost(
                    layers[i - 1][pj], cand, gb=up_gb,
                    minimize=minimize)
                if val < best:
                    best, arg = val, pj
            costs[i][j] = best
            parents[i][j] = arg
    j = min(costs[-1], key=costs[-1].get)  # type: ignore[arg-type]
    choice: Dict[object, Candidate] = {}
    for i in range(len(layers) - 1, -1, -1):
        choice[order[i]] = layers[i][j]
        if i > 0:
            j = parents[i][j]
    return choice


def _optimize_general_bb(dag, per_task, minimize) -> Dict[object, Candidate]:
    """Exact branch-and-bound for general DAGs (parity role:
    _optimize_by_ilp, sky/optimizer.py:470 — without the pulp dependency).

    Candidates per task are capped to the top-K to bound the search; the
    remaining tail is still available to the failover provisioner.
    """
    topk = 8
    order = dag.topological_order()
    layers = [per_task[t][:topk] for t in order]
    graph = dag.get_graph()
    index = {t: i for i, t in enumerate(order)}
    preds: List[List[int]] = [
        [index[p] for p in graph.predecessors(t)] for t in order
    ]
    # Lower bound: sum of per-task minima for unassigned tasks.
    min_rest = [0.0] * (len(order) + 1)
    for i in range(len(order) - 1, -1, -1):
        min_rest[i] = min_rest[i + 1] + min(
            _objective(c, minimize) for c in layers[i])
    best_val = float('inf')
    best_assign: Optional[List[int]] = None
    assign: List[int] = [-1] * len(order)

    def _dfs(i: int, acc: float):
        nonlocal best_val, best_assign
        if acc + min_rest[i] >= best_val:
            return
        if i == len(order):
            best_val, best_assign = acc, assign.copy()
            return
        for j, cand in enumerate(layers[i]):
            extra = _objective(cand, minimize)
            for p in preds[i]:
                up_gb = getattr(order[p], 'estimated_outputs_gb', None)
                extra += _egress_cost(layers[p][assign[p]], cand,
                                      gb=up_gb, minimize=minimize)
            assign[i] = j
            _dfs(i + 1, acc + extra)
        assign[i] = -1

    _dfs(0, 0.0)
    assert best_assign is not None
    return {t: layers[i][best_assign[i]] for i, t in enumerate(order)}


def _print_plan(dag, choice: Dict[object, Candidate],
                minimize: OptimizeTarget) -> None:
    rows = []
    total_cost = 0.0
    for task in dag.topological_order():
        cand = choice[task]
        total_cost += cand.total_cost
        rows.append((task.name or '-', cand.resources.pretty(),
                     cand.zone or cand.region,
                     f'${cand.cost_per_hour:.2f}/hr',
                     f'~{cand.duration_hours:.2f}h',
                     f'${cand.total_cost:.2f}'))
    name_w = max(4, max(len(r[0]) for r in rows)) + 2
    res_w = max(9, max(len(r[1]) for r in rows)) + 2
    zone_w = max(4, max(len(r[2]) for r in rows)) + 2
    print(ux.emph(f'Optimizer plan (minimizing {minimize.value}):'))
    header = (f'  {"TASK":<{name_w}}{"RESOURCES":<{res_w}}'
              f'{"ZONE":<{zone_w}}{"PRICE":<12}{"EST.TIME":<10}{"EST.COST"}')
    print(header)
    for r in rows:
        print(f'  {r[0]:<{name_w}}{r[1]:<{res_w}}{r[2]:<{zone_w}}'
              f'{r[3]:<12}{r[4]:<10}{r[5]}')
    print(f'  Estimated total cost: ${total_cost:.2f}')
