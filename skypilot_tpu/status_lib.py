"""Shared status enums for clusters and storage."""
import enum


class ClusterStatus(enum.Enum):
    """Cluster lifecycle states (parity: reference ClusterStatus).

    INIT: provisioning in progress, or the cluster is in an abnormal state
        (e.g. partial slice failure detected during refresh).
    UP: the slice exists and the podlet runtime is healthy on all hosts.
    STOPPED: instances stopped but resumable (CPU VMs only — TPU slices
        generally cannot stop; see clouds/gcp.py).
    """
    INIT = 'INIT'
    UP = 'UP'
    STOPPED = 'STOPPED'

    def colored_str(self) -> str:
        from skypilot_tpu.utils import ux
        color = {
            ClusterStatus.INIT: ux.Color.BLUE,
            ClusterStatus.UP: ux.Color.GREEN,
            ClusterStatus.STOPPED: ux.Color.YELLOW,
        }[self]
        return ux.colored(self.value, color)


class StorageStatus(enum.Enum):
    INIT = 'INIT'
    UPLOAD_FAILED = 'UPLOAD_FAILED'
    READY = 'READY'
    DELETED = 'DELETED'
