"""Hardware requirement model — TPU pod slices are the atomic unit.

Parity: sky/resources.py:30 (``Resources``) with the reference's semantics —
feasibility ordering (less_demanding_than), blocklist matching
(should_be_blocked_by), YAML round-trip, cost estimation, deploy-variable
generation — but re-designed for TPU-first placement: instead of
(cloud, instance_type, accelerator-on-VM), the primary axis is
(accelerator slice shape, zone, spot/reservation).  CPU-only VMs (for the
jobs/serve controllers) are the secondary axis via instance_type/cpus.
"""
import textwrap
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.utils import ux

_DEFAULT_DISK_SIZE_GB = 256


class Resources:
    """A (possibly partial) hardware requirement.

    Examples::

        Resources(accelerator='tpu-v5e-8')
        Resources(accelerator='v6e-64', zone='us-east5-b', use_spot=True)
        Resources(cloud='gcp', cpus='8+')              # controller VM
        Resources(cloud='local')                        # dev/test backend
    """

    _VERSION = 1

    def __init__(
        self,
        cloud: Optional[str] = None,
        accelerator: Optional[str] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        cpus: Optional[Union[int, float, str]] = None,
        memory: Optional[Union[int, float, str]] = None,
        instance_type: Optional[str] = None,
        use_spot: bool = False,
        job_recovery: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        image_id: Optional[str] = None,
        disk_size: Optional[int] = None,
        ports: Optional[List[Union[int, str]]] = None,
        labels: Optional[Dict[str, str]] = None,
        reservation: Optional[str] = None,
        autostop: Optional[Dict[str, Any]] = None,
        tp_size: Optional[int] = None,
    ):
        self._version = self._VERSION
        self._cloud = cloud.lower() if cloud else None
        self._accelerator: Optional[str] = None
        if accelerator is not None:
            self._accelerator = catalog.canonicalize(accelerator)
            if self._cloud is None:
                self._cloud = 'gcp'
        self._accelerator_args = dict(accelerator_args or {})
        self._cpus = str(cpus) if cpus is not None else None
        self._memory = str(memory) if memory is not None else None
        self._instance_type = instance_type
        self._use_spot = bool(use_spot)
        self._job_recovery = job_recovery
        self._region = region
        self._zone = zone
        self._image_id = image_id
        self._disk_size = int(disk_size) if disk_size else _DEFAULT_DISK_SIZE_GB
        self._ports = [str(p) for p in ports] if ports else None
        self._labels = dict(labels) if labels else None
        self._reservation = reservation
        self._autostop = autostop
        self._tp_size = int(tp_size) if tp_size is not None else None
        self._validate()

    # ------------------------------------------------------------ properties

    @property
    def cloud(self) -> Optional[str]:
        return self._cloud

    @property
    def accelerator(self) -> Optional[str]:
        return self._accelerator

    @property
    def accelerator_args(self) -> Dict[str, Any]:
        return self._accelerator_args

    @property
    def runtime_version(self) -> Optional[str]:
        """TPU software version; catalog default when unspecified."""
        if self._accelerator is None:
            return None
        rv = self._accelerator_args.get('runtime_version')
        return rv or catalog.default_runtime_version(self._accelerator)

    @property
    def cpus(self) -> Optional[str]:
        return self._cpus

    @property
    def memory(self) -> Optional[str]:
        return self._memory

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def job_recovery(self) -> Optional[str]:
        return self._job_recovery

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def ports(self) -> Optional[List[str]]:
        return self._ports

    @property
    def labels(self) -> Optional[Dict[str, str]]:
        return self._labels

    @property
    def reservation(self) -> Optional[str]:
        return self._reservation

    @property
    def autostop(self) -> Optional[Dict[str, Any]]:
        return self._autostop

    @property
    def tp_size(self) -> Optional[int]:
        """Tensor-parallel degree each serving replica shards over.

        None means unsharded (single-chip engine).  Consumed by the serve
        plane: ReplicaManager exports it as SKYTPU_SERVE_TP_SIZE so the
        replica's inference server builds a tp mesh and head-shards its
        paged KV pool.
        """
        return self._tp_size

    @property
    def is_tpu(self) -> bool:
        return self._accelerator is not None

    @property
    def slice_info(self) -> Optional[catalog.SliceInfo]:
        if self._accelerator is None:
            return None
        return catalog.get_slice_info(self._accelerator)

    @property
    def num_hosts(self) -> int:
        """Hosts per node: a multi-host slice is 1 node with many hosts.

        Parity: the reference models the same thing as num_ips_per_node
        (sky/backends/cloud_vm_ray_backend.py:2469).
        """
        info = self.slice_info
        return info.hosts if info is not None else 1

    @property
    def chips_per_host(self) -> int:
        info = self.slice_info
        return info.chips_per_host if info is not None else 0

    @property
    def need_cleanup_after_preemption(self) -> bool:
        """Preempted TPU slices must be deleted, not restarted.

        Parity: sky/resources.py:622 (consulted by the managed-jobs
        controller before relaunch, sky/jobs/controller.py:320-329).
        """
        return self.is_tpu and self._use_spot

    # ------------------------------------------------------------ validation

    def _validate(self) -> None:
        if self._cloud == 'k8s':
            self._cloud = 'kubernetes'    # accepted alias
        if self._cloud is not None and self._cloud not in (
                'gcp', 'local', 'kubernetes'):
            raise exceptions.InvalidResourcesError(
                f'Unknown cloud {self._cloud!r}; supported: gcp, local, '
                'kubernetes.')
        if self._accelerator is not None:
            if self._instance_type is not None:
                raise exceptions.InvalidResourcesError(
                    'Cannot specify both accelerator and instance_type; the '
                    'TPU slice shape determines its host VMs.')
            catalog.get_slice_info(self._accelerator)  # raises if unknown
            if self._cloud not in ('local', 'kubernetes'):
                # local simulates slices in its own zones (local-a/b/c);
                # kubernetes places onto whatever node pools the
                # connected cluster has — only GCP placements validate
                # against the catalog's zone offerings.
                catalog.validate_region_zone(self._accelerator, self._region,
                                             self._zone)
            bad_keys = set(self._accelerator_args) - {
                'runtime_version', 'network', 'subnetwork', 'best_effort',
                'queued_resource',
            }
            if bad_keys:
                raise exceptions.InvalidResourcesError(
                    f'Unknown accelerator_args: {sorted(bad_keys)}')
        for spec, name in ((self._cpus, 'cpus'), (self._memory, 'memory')):
            if spec is None:
                continue
            body = spec[:-1] if spec.endswith('+') else spec
            try:
                float(body)
            except ValueError:
                raise exceptions.InvalidResourcesError(
                    f'Invalid {name} spec {spec!r}; expected "8" or "8+".'
                    ) from None
        if self._ports:
            for p in self._ports:
                parts = p.split('-')
                if not all(x.isdigit() for x in parts) or len(parts) > 2:
                    raise exceptions.InvalidResourcesError(
                        f'Invalid port spec {p!r}; expected "8080" or '
                        f'"10000-10010".')
        if self._tp_size is not None and self._tp_size < 1:
            raise exceptions.InvalidResourcesError(
                f'tp_size must be >= 1, got {self._tp_size}.')

    # ---------------------------------------------------------------- costs

    def get_cost(self, seconds: float) -> float:
        """Estimated $ for running this many seconds."""
        hours = seconds / 3600.0
        if self._cloud in ('local', 'kubernetes'):
            return 0.0
        if self._accelerator is not None:
            hourly = catalog.get_hourly_cost(self._accelerator,
                                             use_spot=self._use_spot,
                                             region=self._region,
                                             zone=self._zone)
        else:
            instance = self._instance_type or catalog.get_vm_for_cpus(
                self._cpus, self._memory)
            if instance is None:
                raise exceptions.ResourcesUnavailableError(
                    f'No VM type satisfies cpus={self._cpus} '
                    f'memory={self._memory}.')
            hourly = catalog.get_vm_hourly_cost(instance,
                                                use_spot=self._use_spot,
                                                region=self._region,
                                                zone=self._zone)
        return hourly * hours

    # ---------------------------------------------------- feasibility order

    def less_demanding_than(self, other: 'Resources') -> bool:
        """True if self's requirements are satisfied by `other`'s concrete
        resources.  Parity: sky/resources.py:1107."""
        if self._cloud is not None and self._cloud != other._cloud:
            return False
        if self._region is not None and self._region != other._region:
            return False
        if self._zone is not None and self._zone != other._zone:
            return False
        if self._accelerator is not None:
            if self._accelerator != other._accelerator:
                return False
            mine = self._accelerator_args.get('runtime_version')
            theirs = other._accelerator_args.get('runtime_version')
            if mine is not None and theirs is not None and mine != theirs:
                return False
        if self._use_spot != other._use_spot:
            return False
        if self._instance_type is not None:
            if self._instance_type != other._instance_type:
                return False

        def _satisfies(spec: Optional[str], actual: Optional[float]) -> bool:
            if spec is None:
                return True
            if actual is None:
                return False
            if spec.endswith('+'):
                return actual >= float(spec[:-1])
            return actual == float(spec)

        if self._cpus is not None or self._memory is not None:
            if other._instance_type is not None:
                vcpus, mem = catalog.get_vm_info(other._instance_type)
            elif other.is_tpu:
                vcpus, mem = 96.0, 192.0  # TPU-VM hosts are large
            else:
                vcpus, mem = None, None
            if not _satisfies(self._cpus, vcpus):
                return False
            if not _satisfies(self._memory, mem):
                return False
        if self._image_id is not None and self._image_id != other._image_id:
            return False
        if other._disk_size < self._disk_size:
            return False
        return True

    def should_be_blocked_by(self, blocked: 'Resources') -> bool:
        """Subset matching against a failover blocklist entry.

        Parity: sky/resources.py:1207.  A blocked entry with a field set to
        None matches any value of that field.
        """
        return ((blocked._cloud is None or blocked._cloud == self._cloud) and
                (blocked._accelerator is None or
                 blocked._accelerator == self._accelerator) and
                (blocked._instance_type is None or
                 blocked._instance_type == self._instance_type) and
                (blocked._region is None or blocked._region == self._region)
                and (blocked._zone is None or blocked._zone == self._zone) and
                (blocked._use_spot == self._use_spot))

    # ------------------------------------------------------------- mutation

    def copy(self, **override) -> 'Resources':
        fields = dict(
            cloud=self._cloud,
            accelerator=self._accelerator,
            accelerator_args=dict(self._accelerator_args),
            cpus=self._cpus,
            memory=self._memory,
            instance_type=self._instance_type,
            use_spot=self._use_spot,
            job_recovery=self._job_recovery,
            region=self._region,
            zone=self._zone,
            image_id=self._image_id,
            disk_size=self._disk_size,
            ports=self._ports,
            labels=self._labels,
            reservation=self._reservation,
            autostop=self._autostop,
            tp_size=self._tp_size,
        )
        fields.update(override)
        return Resources(**fields)

    # ------------------------------------------------------------ YAML i/o

    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]) -> 'Resources':
        if config is None:
            return cls()
        config = dict(config)
        known = {
            'cloud', 'accelerator', 'accelerators', 'accelerator_args',
            'cpus', 'memory', 'instance_type', 'use_spot', 'job_recovery',
            'region', 'zone', 'image_id', 'disk_size', 'ports', 'labels',
            'reservation', 'autostop', 'any_of', 'tp_size'
        }
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidTaskError(
                f'Unknown resources fields: {sorted(unknown)}')
        acc_singular = config.pop('accelerator', None)
        acc_plural = config.pop('accelerators', None)
        if acc_singular is not None and acc_plural is not None:
            raise exceptions.InvalidTaskError(
                "Specify either 'accelerator' or 'accelerators', not both.")
        acc = acc_singular if acc_singular is not None else acc_plural
        if isinstance(acc, dict):
            # reference-style {'V100': 4} mapping; a TPU slice is a single
            # string and its shape already encodes the count.
            if len(acc) != 1 or next(iter(acc.values())) not in (1, None):
                raise exceptions.InvalidTaskError(
                    'accelerators mapping must be a single entry with count '
                    "1; TPU slice shapes encode their own size (use e.g. "
                    "accelerator: tpu-v5e-16, or num_nodes for multiple "
                    'slices).')
            acc = next(iter(acc))
        ports = config.pop('ports', None)
        if ports is not None and not isinstance(ports, list):
            ports = [ports]
        config.pop('any_of', None)  # handled by Task
        return cls(accelerator=acc, ports=ports, **config)

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}

        def put(k, v):
            if v is not None and v != {} and v != []:
                cfg[k] = v

        put('cloud', self._cloud)
        put('accelerator', self._accelerator)
        put('accelerator_args', self._accelerator_args or None)
        put('cpus', self._cpus)
        put('memory', self._memory)
        put('instance_type', self._instance_type)
        if self._use_spot:
            cfg['use_spot'] = True
        put('job_recovery', self._job_recovery)
        put('region', self._region)
        put('zone', self._zone)
        put('image_id', self._image_id)
        if self._disk_size != _DEFAULT_DISK_SIZE_GB:
            cfg['disk_size'] = self._disk_size
        put('ports', self._ports)
        put('labels', self._labels)
        put('reservation', self._reservation)
        put('autostop', self._autostop)
        put('tp_size', self._tp_size)
        return cfg

    # ------------------------------------------------------------- dunders

    def __repr__(self) -> str:
        parts = []
        if self._cloud:
            parts.append(self._cloud.upper() if self._cloud == 'gcp' else
                         self._cloud)
        if self._accelerator:
            spot = '[Spot]' if self._use_spot else ''
            parts.append(f'{self._accelerator}{spot}')
            info = self.slice_info
            if info and info.is_multi_host:
                parts.append(f'({info.hosts} hosts)')
        elif self._instance_type:
            spot = '[Spot]' if self._use_spot else ''
            parts.append(f'{self._instance_type}{spot}')
        else:
            if self._cpus:
                parts.append(f'cpus={self._cpus}')
            if self._memory:
                parts.append(f'mem={self._memory}')
        if self._zone:
            parts.append(f'zone={self._zone}')
        elif self._region:
            parts.append(f'region={self._region}')
        return '<Resources: ' + ' '.join(parts or ['(empty)']) + '>'

    def pretty(self) -> str:
        if self._accelerator:
            base = self._accelerator
            if self._use_spot:
                base += ' ' + ux.colored('[spot]', ux.Color.YELLOW)
            return base
        return self._instance_type or f'cpus={self._cpus or "any"}'

    def __eq__(self, other) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        return hash(
            tuple(
                sorted((k, str(v)) for k, v in self.to_yaml_config().items())))

    def __setstate__(self, state):
        """Unpickle with forward-compat version handling (handles are
        pickled into the state DB; parity: reference __setstate__ chains)."""
        version = state.get('_version', 0)
        if version < 1:
            state.setdefault('_reservation', None)
            state.setdefault('_autostop', None)
        self.__dict__.update(state)


def format_resources_table(resources_list: List[Resources]) -> str:
    lines = []
    for r in resources_list:
        cost = r.get_cost(3600)
        lines.append(f'  {r.pretty():30s} ${cost:.2f}/hr')
    return textwrap.indent('\n'.join(lines), '')
