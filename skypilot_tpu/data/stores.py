"""Destination stores: where a Storage object's bucket actually lives.

Parity: sky/data/storage.py's five store classes (S3Store :1080,
GcsStore :1527, AzureBlobStore :1973, R2Store :2752, IBMCosStore
:3138) — reduced to the TPU-relevant contract.  The TPU-first stance
is unchanged: GCS is the serving-side store (gcsfuse MOUNT on TPU
VMs); s3/r2/azure/cos are DESTINATION stores for task outputs and
cross-cloud datasets, reached through external tools exactly like the
reference (gsutil speaks s3:// natively; r2/azure/cos go through a
configured rclone remote) — no cloud SDK imports.

MOUNT semantics: only GCS mounts on a TPU VM (gcsfuse).  A MOUNT
request against any other store degrades to COPY with a warning, the
same contract as the FUSE-less-host downgrade (storage_mounting).
"""
import shutil
import subprocess
from typing import Dict, List, Optional, Type

from skypilot_tpu import exceptions, logsys

logger = logsys.init_logger(__name__)


def _run(cmd: List[str]) -> subprocess.CompletedProcess:
    """Single seam for tests to intercept tool invocations."""
    return subprocess.run(cmd, capture_output=True, text=True, check=False)


class Store:
    """Bucket operations for one destination cloud."""

    NAME = 'abstract'
    SCHEME = ''
    MOUNTABLE = False
    # stderr substrings meaning "bucket already gone" (delete stays
    # idempotent per tool: gsutil/aws/rclone each phrase it their way).
    MISSING_MARKERS: tuple = ()
    _REGISTRY: Dict[str, Type['Store']] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if cls.NAME != 'abstract':
            Store._REGISTRY[cls.NAME] = cls

    @classmethod
    def make(cls, name: Optional[str]) -> 'Store':
        store_cls = cls._REGISTRY.get((name or 'gcs').lower())
        if store_cls is None:
            raise exceptions.StorageError(
                f'Unknown store {name!r}; one of '
                f'{sorted(cls._REGISTRY)}')
        return store_cls()

    def uri(self, bucket_name: str) -> str:
        return f'{self.SCHEME}{bucket_name}'

    # Each op returns a CompletedProcess (rc + stderr for callers).
    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def create(self, uri: str) -> subprocess.CompletedProcess:
        raise NotImplementedError

    def delete(self, uri: str) -> subprocess.CompletedProcess:
        raise NotImplementedError

    def sync_up(self, local_src: str, uri: str,
                is_dir: bool) -> subprocess.CompletedProcess:
        raise NotImplementedError

    def host_copy_command(self, uri: str, dst: str) -> str:
        """Shell command a cluster HOST runs to COPY the bucket down."""
        raise NotImplementedError


class GcsStore(Store):
    """gsutil (gcloud storage fallback) — the default, mountable store."""

    NAME = 'gcs'
    SCHEME = 'gs://'
    MOUNTABLE = True
    MISSING_MARKERS = ('BucketNotFound', 'NotFoundException')

    def _tool(self, args: List[str]) -> subprocess.CompletedProcess:
        # Routed through storage._run_gsutil — the long-standing seam
        # tests (and callers) already intercept.
        from skypilot_tpu.data import storage as storage_mod
        return storage_mod._run_gsutil(args, check=False)

    def exists(self, uri: str) -> bool:
        return self._tool(['ls', uri]).returncode == 0

    def create(self, uri: str) -> subprocess.CompletedProcess:
        return self._tool(['mb', uri])

    def delete(self, uri: str) -> subprocess.CompletedProcess:
        return self._tool(['rm', '-r', uri])

    def sync_up(self, local_src: str, uri: str, is_dir: bool):
        return self._tool(['rsync', '-r', local_src, uri] if is_dir
                          else ['cp', local_src, uri])

    def host_copy_command(self, uri: str, dst: str) -> str:
        import shlex
        d = shlex.quote(dst)
        return (f'mkdir -p {d} && '
                f'(command -v gsutil >/dev/null && '
                f'gsutil -m rsync -r {uri} {d} || '
                f'gcloud storage rsync --recursive {uri} {d})')


class S3Store(Store):
    """AWS S3 destination: gsutil (speaks s3:// with boto/AWS-env
    credentials — one tool shared with GCS), aws CLI fallback."""

    NAME = 's3'
    SCHEME = 's3://'
    MOUNTABLE = False   # goofys not assumed on TPU images -> COPY
    MISSING_MARKERS = ('NoSuchBucket', 'BucketNotFound')

    def _tool(self, gsutil_args: List[str], aws_args: List[str]
              ) -> subprocess.CompletedProcess:
        if shutil.which('gsutil'):
            return _run(['gsutil', '-m'] + gsutil_args)
        if shutil.which('aws'):
            return _run(['aws', 's3'] + aws_args)
        raise exceptions.StorageError(
            'Neither gsutil (with S3 credentials in ~/.boto or AWS env '
            'vars) nor the aws CLI found; cannot manage s3:// buckets.')

    def exists(self, uri: str) -> bool:
        return self._tool(['ls', uri], ['ls', uri]).returncode == 0

    def create(self, uri: str) -> subprocess.CompletedProcess:
        return self._tool(['mb', uri], ['mb', uri])

    def delete(self, uri: str) -> subprocess.CompletedProcess:
        return self._tool(['rm', '-r', uri], ['rb', '--force', uri])

    def sync_up(self, local_src: str, uri: str, is_dir: bool):
        return self._tool(
            ['rsync', '-r', local_src, uri] if is_dir
            else ['cp', local_src, uri],
            ['sync', local_src, uri] if is_dir
            else ['cp', local_src, uri])

    def host_copy_command(self, uri: str, dst: str) -> str:
        import shlex
        d = shlex.quote(dst)
        return (f'mkdir -p {d} && '
                f'(command -v gsutil >/dev/null && '
                f'gsutil -m rsync -r {uri} {d} || '
                f'aws s3 sync {uri} {d})')


class RcloneStore(Store):
    """Destinations reached through a configured rclone remote: the
    remote's config carries what no generic tool can guess (R2 account
    endpoint, Azure connection string / SAS, COS endpoint) — the same
    contract as the reference's rclone paths and data_transfer's
    ingestion.  Subclasses set NAME/SCHEME and the REMOTE name users
    configure once with `rclone config`."""

    NAME = 'abstract'
    REMOTE = ''
    MOUNTABLE = False
    MISSING_MARKERS = ('directory not found', "doesn't exist")

    @classmethod
    def _remote_path(cls, uri: str) -> str:
        return f'{cls.REMOTE}:' + uri[len(cls.SCHEME):].rstrip('/')

    def _tool(self, args: List[str]) -> subprocess.CompletedProcess:
        if not shutil.which('rclone'):
            raise exceptions.StorageError(
                f'rclone not found; {self.SCHEME} buckets need rclone '
                f'with a {self.REMOTE!r} remote configured '
                '(rclone config).')
        return _run(['rclone'] + args)

    def exists(self, uri: str) -> bool:
        return self._tool(['lsd', self._remote_path(uri)]).returncode == 0

    def create(self, uri: str) -> subprocess.CompletedProcess:
        return self._tool(['mkdir', self._remote_path(uri)])

    def delete(self, uri: str) -> subprocess.CompletedProcess:
        return self._tool(['purge', self._remote_path(uri)])

    def sync_up(self, local_src: str, uri: str, is_dir: bool):
        # 'copy', never 'sync': sync would DELETE destination objects
        # absent from the source — gsutil rsync (no -d) and aws s3 sync
        # are non-deleting, and a persistent bucket's prior outputs
        # must survive a re-upload.
        dst = self._remote_path(uri)
        if not is_dir:
            import os
            return self._tool(
                ['copyto', local_src,
                 f'{dst}/{os.path.basename(local_src)}'])
        return self._tool(['copy', local_src, dst])

    def host_copy_command(self, uri: str, dst: str) -> str:
        import shlex
        return (f'mkdir -p {shlex.quote(dst)} && '
                f'rclone copy --fast-list {self._remote_path(uri)} '
                f'{shlex.quote(dst)}')


class R2Store(RcloneStore):
    """Cloudflare R2 (S3-compatible, but the account endpoint only
    rclone config carries).  Parity: reference R2Store
    (sky/data/storage.py:2752)."""

    NAME = 'r2'
    SCHEME = 'r2://'
    REMOTE = 'r2'


class AzureBlobStore(RcloneStore):
    """Azure Blob destination via a configured 'azure' rclone remote
    (azureblob backend: connection string / SAS / MSI live in rclone
    config — no Azure SDK import).  Parity: reference AzureBlobStore
    (sky/data/storage.py:1973), reduced to the TPU-relevant contract:
    COPY destination for task outputs (blobfuse2 MOUNT is not assumed
    on TPU images; MOUNT degrades to COPY like s3/r2)."""

    NAME = 'azure'
    SCHEME = 'azure://'
    REMOTE = 'azure'
    # The base markers were tuned on rclone's S3-compatible backends;
    # azureblob phrases a missing container differently (the service
    # error code ContainerNotFound and rclone's own wording).  Without
    # these, deleting an already-gone azure:// bucket loses its
    # idempotency and surfaces as a hard StorageError.
    MISSING_MARKERS = RcloneStore.MISSING_MARKERS + (
        'container not found', 'ContainerNotFound',
        'container does not exist')


class IbmCosStore(RcloneStore):
    """IBM Cloud Object Storage destination via a configured 'cos'
    rclone remote (S3-compatible; the endpoint lives in rclone
    config).  Parity: reference IBMCosStore
    (sky/data/storage.py:3138); cos:// was previously source-only
    here (data_transfer ingestion) — this closes the destination
    direction."""

    NAME = 'cos'
    SCHEME = 'cos://'
    REMOTE = 'cos'
