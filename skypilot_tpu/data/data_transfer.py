"""Cross-cloud bucket ingestion: copy s3://, r2://, cos:// or azure://
into GCS.

Parity: sky/data/data_transfer.py:39-193 (GCS Transfer Service + rclone
fallbacks).  TPU-first stance: the *serving* side of storage stays GCS —
gcsfuse MOUNT on TPU VMs, gsutil COPY — and external-cloud sources are
ingested by a one-way transfer into a GCS bucket at upload time, so a
finetune task can declare `source: s3://my-datasets/c4` and the slice
only ever talks to GCS.

Tool strategy (first available wins):

  s3://  -> `gsutil rsync` directly from S3 (gsutil reads s3:// when
            ~/.boto or AWS env credentials exist), else `rclone`.
  r2://  -> `rclone` (Cloudflare R2 is S3-compatible but needs the
            account endpoint, which only rclone config carries).
  cos:// -> `rclone` (IBM COS, same reasoning).
  azure:// -> `rclone` (azureblob backend; connection string / SAS in
            rclone config).

No cloud SDK imports: both tools are external binaries, matching the
reference's delegation (SURVEY.md §2: rsync/rclone/goofys are processes,
not libraries).
"""
import shutil
import subprocess
from typing import List, Optional, Tuple

from skypilot_tpu import exceptions, logsys

logger = logsys.init_logger(__name__)

_SUPPORTED_SCHEMES = ('s3://', 'r2://', 'cos://', 'azure://')


def is_external_cloud_uri(uri: str) -> bool:
    return isinstance(uri, str) and uri.startswith(_SUPPORTED_SCHEMES)


def _run(cmd: List[str]) -> subprocess.CompletedProcess:
    """Single seam for tests to intercept tool invocations."""
    return subprocess.run(cmd, capture_output=True, text=True, check=False)


def _split(uri: str) -> Tuple[str, str]:
    """'s3://bucket/pre/fix' -> ('s3', 'bucket/pre/fix')."""
    scheme, rest = uri.split('://', 1)
    return scheme, rest.rstrip('/')


def _gsutil_base() -> Optional[List[str]]:
    if shutil.which('gsutil'):
        return ['gsutil', '-m']
    return None


def _rclone_remote(scheme: str) -> str:
    """Conventional rclone remote name per scheme; users configure the
    matching remote once (`rclone config`) — same contract as the
    reference's rclone path (sky/data/data_transfer.py:150)."""
    return {'s3': 's3', 'r2': 'r2', 'cos': 'cos',
            'azure': 'azure'}[scheme]


def transfer_to_gcs(src_uri: str, dst_gcs_uri: str) -> None:
    """Copy an external-cloud bucket path into a gs:// destination.

    Raises StorageError when no capable tool is installed or the copy
    fails; the error message says exactly what to install/configure.
    """
    scheme, src_path = _split(src_uri)
    dst = dst_gcs_uri.rstrip('/')
    attempts = []
    if scheme == 's3':
        gsutil = _gsutil_base()
        if gsutil is not None:
            # gsutil speaks s3:// natively with boto/AWS-env credentials:
            # one tool, server-side-ish streaming, no staging disk.
            res = _run(gsutil + ['rsync', '-r', f's3://{src_path}', dst])
            if res.returncode == 0:
                logger.info('Transferred %s -> %s via gsutil.', src_uri,
                            dst)
                return
            attempts.append(f'gsutil: {res.stderr[-300:]}')
    if shutil.which('rclone'):
        remote = _rclone_remote(scheme)
        res = _run(['rclone', 'copy', '--fast-list',
                    f'{remote}:{src_path}', f'gcs:{_split(dst)[1]}'])
        if res.returncode == 0:
            logger.info('Transferred %s -> %s via rclone.', src_uri, dst)
            return
        attempts.append(f'rclone: {res.stderr[-300:]}')
    if not attempts:
        raise exceptions.StorageError(
            f'No tool available to ingest {src_uri}: install gsutil '
            '(with S3 credentials in ~/.boto or AWS env vars) or rclone '
            f'(with a {_rclone_remote(scheme)!r} remote and a "gcs" '
            'remote configured).')
    raise exceptions.StorageError(
        f'Ingesting {src_uri} -> {dst} failed: ' + ' | '.join(attempts))
