"""Mount/copy buckets onto cluster hosts.

Parity: sky/data/mounting_utils.py — gcsfuse for MOUNT, gsutil for COPY.
On the local cloud, MOUNT degrades to a COPY into the host dir (gcsfuse
needs privileged FUSE), logged as such.
"""
from typing import List

from skypilot_tpu import logsys
from skypilot_tpu.data.storage import Storage, StorageMode
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils.command_runner import (CommandRunner,
                                               LocalProcessRunner)

logger = logsys.init_logger(__name__)

_GCSFUSE_VERSION = '2.5.1'

_INSTALL_GCSFUSE = (
    'command -v gcsfuse >/dev/null || { '
    'curl -sSL -o /tmp/gcsfuse.deb '
    'https://github.com/GoogleCloudPlatform/gcsfuse/releases/download/'
    f'v{_GCSFUSE_VERSION}/gcsfuse_{_GCSFUSE_VERSION}_amd64.deb && '
    'sudo dpkg -i /tmp/gcsfuse.deb; }')


def mount_command(bucket: str, mount_path: str) -> str:
    return (f'{_INSTALL_GCSFUSE} && '
            f'mkdir -p {mount_path} && '
            f'mountpoint -q {mount_path} || '
            f'gcsfuse --implicit-dirs {bucket} {mount_path}')


def copy_command(bucket_uri: str, dst: str) -> str:
    """Directory sync: bucket -> dst dir."""
    import shlex
    d = shlex.quote(dst)
    return (f'mkdir -p {d} && '
            f'(command -v gsutil >/dev/null && '
            f'gsutil -m rsync -r {bucket_uri} {d} || '
            f'gcloud storage rsync --recursive {bucket_uri} {d})')


def copy_object_command(src_uri: str, dst: str) -> str:
    """Single object/prefix copy: gs://... -> dst path (file mounts)."""
    import shlex
    d = shlex.quote(dst)
    return (f'mkdir -p $(dirname {d}) && '
            f'(command -v gsutil >/dev/null && '
            f'gsutil -m cp -r {src_uri} {d} || '
            f'gcloud storage cp -r {src_uri} {d})')


def mount_storage(runners: List[CommandRunner], mount_path: str,
                  storage: Storage, log_path: str) -> None:
    if storage.source is not None and not str(
            storage.source).startswith('gs://'):
        storage.upload()
    bucket = storage.bucket_uri.removeprefix('gs://')
    if storage.mode == StorageMode.MOUNT:
        if any(isinstance(r, LocalProcessRunner) for r in runners):
            logger.warning(
                'MOUNT degrades to COPY on the local cloud (no FUSE).')
            cmd = copy_command(storage.bucket_uri, mount_path)
        else:
            cmd = mount_command(bucket, mount_path)
    else:
        cmd = copy_command(storage.bucket_uri, mount_path)
    subprocess_utils.run_in_parallel(
        lambda r: r.run_or_raise(cmd, log_path=log_path), runners)
