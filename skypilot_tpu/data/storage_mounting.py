"""Mount/copy buckets onto cluster hosts.

Parity: sky/data/mounting_utils.py:24-159 — gcsfuse for MOUNT, gsutil
for COPY, with environment-aware degradation: the reference installs
FUSE adapters per-environment; here a per-host probe decides whether
MOUNT is even possible and degrades to COPY (with a warning) when it is
not, instead of failing the task at setup:

- local cloud: always COPY (fake hosts, no FUSE);
- kubernetes pods: no /dev/fuse unless the pod is privileged — plain
  pods degrade to COPY.  To keep a real MOUNT, run a privileged pod
  (`securityContext: {privileged: true}`) or a gcsfuse sidecar
  (GKE's `gke-gcsfuse/volumes: "true"` annotation) — docs/storage.md;
- hardened VMs without passwordless sudo (and non-root): the gcsfuse
  install cannot run — degrade to COPY rather than die in setup.
"""
import os
from typing import List

from skypilot_tpu import logsys
from skypilot_tpu.data.storage import Storage, StorageMode
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils.command_runner import (CommandRunner,
                                               LocalProcessRunner)

logger = logsys.init_logger(__name__)

_GCSFUSE_VERSION = '2.5.1'

# Install runs as root directly when we ARE root (pods), else via
# passwordless sudo (the probe has already verified one of the two).
_INSTALL_GCSFUSE = (
    'command -v gcsfuse >/dev/null || { '
    'if [ "$(id -u)" = 0 ]; then SUDO=; else SUDO=sudo; fi; '
    'curl -sSL -o /tmp/gcsfuse.deb '
    'https://github.com/GoogleCloudPlatform/gcsfuse/releases/download/'
    f'v{_GCSFUSE_VERSION}/gcsfuse_{_GCSFUSE_VERSION}_amd64.deb && '
    '$SUDO dpkg -i /tmp/gcsfuse.deb; }')

# One line of output: can this host take a FUSE mount?
#   FUSE_READY   gcsfuse present + /dev/fuse -> mount directly
#   FUSE_INSTALL /dev/fuse + (root | passwordless sudo) -> install+mount
#   NO_FUSE      anything else -> degrade MOUNT to COPY
_FUSE_PROBE = (
    'if command -v gcsfuse >/dev/null && [ -e /dev/fuse ]; then '
    'echo FUSE_READY; '
    'elif [ -e /dev/fuse ] && { [ "$(id -u)" = 0 ] || '
    'sudo -n true 2>/dev/null; }; then echo FUSE_INSTALL; '
    'else echo NO_FUSE; fi')


def mount_command(bucket: str, mount_path: str) -> str:
    return (f'{_INSTALL_GCSFUSE} && '
            f'mkdir -p {mount_path} && '
            f'mountpoint -q {mount_path} || '
            f'gcsfuse --implicit-dirs {bucket} {mount_path}')


def host_supports_fuse(runner: CommandRunner) -> bool:
    """Probe one host for FUSE-mount capability (see _FUSE_PROBE).

    SKYTPU_DISABLE_FUSE=1 on the client forces the COPY downgrade
    everywhere (ops escape hatch for environments where the probe
    passes but the install/network cannot succeed)."""
    if os.environ.get('SKYTPU_DISABLE_FUSE'):
        return False
    if isinstance(runner, LocalProcessRunner):
        return False
    last_err = ''
    for attempt in range(3):
        rc, out, err = runner.run(_FUSE_PROBE, require_outputs=True)
        if rc == 0 and ('FUSE_READY' in out or 'FUSE_INSTALL' in out):
            return True
        if rc == 0 and 'NO_FUSE' in out:
            return False
        # Probe transport failed (kubectl/ssh hiccup): this says nothing
        # about FUSE — downgrading here would silently turn a live
        # checkpoint mount into a one-shot copy.  Retry, then raise.
        last_err = err or out
        import time
        time.sleep(2 * (attempt + 1))
    from skypilot_tpu import exceptions
    raise exceptions.CommandError(
        rc, 'FUSE capability probe',
        f'probe failed on host {runner.node_id} (transport error, not '
        f'a capability answer): {last_err[-300:]}')


def copy_object_command(src_uri: str, dst: str) -> str:
    """Single object/prefix copy: gs://... -> dst path (file mounts)."""
    import shlex
    d = shlex.quote(dst)
    return (f'mkdir -p $(dirname {d}) && '
            f'(command -v gsutil >/dev/null && '
            f'gsutil -m cp -r {src_uri} {d} || '
            f'gcloud storage cp -r {src_uri} {d})')


def mount_storage(runners: List[CommandRunner], mount_path: str,
                  storage: Storage, log_path: str) -> None:
    if storage.source is not None and not str(
            storage.source).startswith(storage.store.SCHEME):
        storage.upload()
    bucket = storage.bucket_uri.removeprefix('gs://')

    # Store mountability is host-independent: decide (and warn) ONCE,
    # not once per host of a 64-host slice.
    if storage.mode == StorageMode.MOUNT and not storage.store.MOUNTABLE:
        # S3/R2 destination stores: no FUSE adapter assumed on TPU
        # images (the reference uses goofys for S3) — degrade to a COPY
        # of the bucket, same contract as the FUSE-less-host downgrade.
        logger.warning(
            'MOUNT of %s degrades to COPY: the %s store is not '
            'mountable on TPU hosts (only gcs mounts, via gcsfuse).',
            storage.bucket_uri, storage.store_name)
        copy_cmd = storage.store.host_copy_command(storage.bucket_uri,
                                                   mount_path)
        subprocess_utils.run_in_parallel(
            lambda r: r.run_or_raise(copy_cmd, log_path=log_path),
            runners)
        return

    def _one(runner: CommandRunner) -> None:
        if storage.mode == StorageMode.MOUNT:
            if host_supports_fuse(runner):
                cmd = mount_command(bucket, mount_path)
            else:
                # VERDICT r2 #8: degrade, don't die — plain pods and
                # no-sudo hosts cannot FUSE-mount.  The data still
                # arrives (one-shot copy); writes after setup stay
                # host-local, unlike a real MOUNT.
                logger.warning(
                    'MOUNT of %s degrades to COPY on host %s (no FUSE '
                    'device, or no root/passwordless-sudo to install '
                    'gcsfuse; pods need a privileged securityContext '
                    'or the GKE gcsfuse sidecar for a live mount — '
                    'docs/storage.md).',
                    storage.bucket_uri, runner.node_id)
                cmd = storage.store.host_copy_command(
                    storage.bucket_uri, mount_path)
        else:
            cmd = storage.store.host_copy_command(storage.bucket_uri,
                                                  mount_path)
        runner.run_or_raise(cmd, log_path=log_path)

    subprocess_utils.run_in_parallel(_one, runners)
