"""Bucket-backed storage: lifecycle + MOUNT/COPY modes.

Parity: sky/data/storage.py (Storage :384, stores :1080-3138,
StorageMode :192) — TPU-first: GCS is the default and the only
MOUNTable store (gcsfuse on TPU VMs — the checkpoint/resume contract
for managed jobs); **s3/r2/azure/cos are destination stores** (`store:
s3|r2|azure|cos`, data/stores.py — all five reference stores) for task
outputs and cross-cloud datasets, reached via gsutil/aws/rclone
subprocesses.  External-cloud *sources* (s3:// / r2:// / cos:// /
azure://) ingest into a GCS bucket at upload time (data_transfer) when
the destination store is GCS.
"""
import enum
import os
import subprocess
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions, logsys, state
from skypilot_tpu.data.stores import Store
from skypilot_tpu.status_lib import StorageStatus
from skypilot_tpu.utils import common

logger = logsys.init_logger(__name__)


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'


class StorageHandle:
    """Pickled record in the local state DB."""

    def __init__(self, name: str, source: Optional[Union[str, List[str]]],
                 mode: StorageMode, persistent: bool,
                 store: str = 'gcs'):
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        self.store = store


def _run_gsutil(args: List[str], check: bool = True
                ) -> subprocess.CompletedProcess:
    for base in (['gsutil', '-m'], ['gcloud', 'storage']):
        try:
            return subprocess.run(base + args, capture_output=True,
                                  text=True, check=check)
        except FileNotFoundError:
            continue
    raise exceptions.StorageError(
        'Neither gsutil nor gcloud found; cannot manage GCS buckets.')


class Storage:
    """A named bucket on one destination store, optionally synced from
    local source(s)."""

    def __init__(self,
                 name: Optional[str] = None,
                 source: Optional[Union[str, List[str]]] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 persistent: bool = True,
                 store: Optional[str] = None):
        if name is None and source is None:
            raise exceptions.StorageSourceError(
                'Storage needs a name and/or a source.')
        if name is None:
            base = os.path.basename(str(source).rstrip('/'))
            name = f'skytpu-{common.get_user_hash()}-{base}'.lower()
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        # Destination store: explicit `store:` wins; a gs:// source
        # implies gcs; everything else defaults to gcs.  Deliberately
        # NOT inferred from s3://-r2://-cos://-azure:// sources: without an
        # explicit `store:`, those keep the TPU-first ingestion
        # semantics (copied INTO a GCS bucket at upload; the slice only
        # talks to GCS).  `store: s3` + `source: s3://b` means "use
        # that S3 bucket directly" instead.
        self.store_name = (store or 'gcs').lower()
        self.store = Store.make(self.store_name)
        self._validate_source()

    def _validate_source(self) -> None:
        from skypilot_tpu.data import data_transfer
        if self._is_external_bucket:
            return   # single-string source naming the bucket itself
        sources = (self.source if isinstance(self.source, list) else
                   [self.source] if self.source else [])
        for src in sources:
            if data_transfer.is_external_cloud_uri(src):
                if self.store_name != 'gcs':
                    raise exceptions.StorageSourceError(
                        f'External source {src} can only be ingested '
                        f'into a GCS-store bucket (store: gcs), not '
                        f'{self.store_name!r}. To use a pre-existing '
                        f'bucket directly, make it the single string '
                        f'source with a matching store.')
                # s3:// / r2:// / cos:// / azure://: ingested into the GCS bucket
                # at upload time (data_transfer.transfer_to_gcs) — the
                # TPU slice itself only ever talks to GCS.  Parity:
                # sky/data/data_transfer.py:39-193.
                continue
            if '://' in str(src):
                # gs:// here, or a bucket URI inside a LIST: neither is
                # a syncable source — a pre-existing bucket must be the
                # SINGLE string source matching the store's scheme.
                raise exceptions.StorageSourceError(
                    f'{src!r} is not usable as a source for a '
                    f'{self.store_name} store: a bucket URI must be '
                    f'the single string source whose scheme matches '
                    f'the store ({self.store.SCHEME}).')
            if not os.path.exists(os.path.expanduser(src)):
                raise exceptions.StorageSourceError(
                    f'Local source not found: {src}')

    # ------------------------------------------------------------- lifecycle

    @property
    def _is_external_bucket(self) -> bool:
        return (isinstance(self.source, str) and
                self.source.startswith(self.store.SCHEME))

    @property
    def bucket_uri(self) -> str:
        if self._is_external_bucket:
            return self.source.rstrip('/')
        return self.store.uri(self.name)

    def ensure_bucket(self) -> None:
        if self._is_external_bucket:
            return  # pre-existing bucket
        if not self.store.exists(self.bucket_uri):
            logger.info('Creating bucket %s.', self.bucket_uri)
            res = self.store.create(self.bucket_uri)
            if res.returncode != 0:
                raise exceptions.StorageBucketCreateError(
                    f'mb failed: {res.stderr[-500:]}')

    def upload(self) -> None:
        """Sync local source(s) into the bucket; external-cloud sources
        (s3:// / r2:// / cos:// / azure://) are ingested via
        data_transfer when the destination store is GCS."""
        from skypilot_tpu.data import data_transfer
        self.ensure_bucket()
        if self._is_external_bucket:
            # Pre-existing bucket IS the storage; nothing to upload.
            state.add_or_update_storage(self.name, self.to_handle(),
                                        StorageStatus.READY)
            return
        sources = (self.source if isinstance(self.source, list) else
                   [self.source] if self.source else [])
        for src in sources:
            if data_transfer.is_external_cloud_uri(src):
                try:
                    data_transfer.transfer_to_gcs(src, self.bucket_uri)
                except exceptions.StorageError as e:
                    state.add_or_update_storage(
                        self.name, self.to_handle(),
                        StorageStatus.UPLOAD_FAILED)
                    raise exceptions.StorageUploadError(str(e)) from e
                continue
            src = os.path.expanduser(src)
            res = self.store.sync_up(src, self.bucket_uri,
                                     is_dir=os.path.isdir(src))
            if res.returncode != 0:
                state.add_or_update_storage(self.name, self.to_handle(),
                                            StorageStatus.UPLOAD_FAILED)
                raise exceptions.StorageUploadError(
                    f'Upload of {src} failed: {res.stderr[-500:]}')
        state.add_or_update_storage(self.name, self.to_handle(),
                                    StorageStatus.READY)

    def delete(self) -> None:
        if self._is_external_bucket:
            logger.info('Not deleting externally-managed bucket %s.',
                        self.bucket_uri)
        else:
            res = self.store.delete(self.bucket_uri)
            if res.returncode != 0 and not any(
                    m in res.stderr for m in self.store.MISSING_MARKERS):
                raise exceptions.StorageBucketDeleteError(
                    f'Deletion of {self.bucket_uri} failed: '
                    f'{res.stderr[-500:]}')
        state.remove_storage(self.name)

    # ----------------------------------------------------------------- yaml

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        mode_str = str(config.get('mode', 'MOUNT')).upper()
        return cls(name=config.get('name'),
                   source=config.get('source'),
                   mode=StorageMode(mode_str),
                   persistent=config.get('persistent', True),
                   store=config.get('store'))

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {'name': self.name, 'mode': self.mode.value}
        if self.source is not None:
            cfg['source'] = self.source
        if not self.persistent:
            cfg['persistent'] = False
        if self.store_name != 'gcs':
            cfg['store'] = self.store_name
        return cfg

    def to_handle(self) -> StorageHandle:
        return StorageHandle(self.name, self.source, self.mode,
                             self.persistent, self.store_name)

    @classmethod
    def from_handle(cls, handle: StorageHandle) -> 'Storage':
        return cls(name=handle.name, source=handle.source, mode=handle.mode,
                   persistent=handle.persistent,
                   store=getattr(handle, 'store', 'gcs'))
