"""Bucket-backed storage: lifecycle + MOUNT/COPY modes.

Parity: sky/data/storage.py (Storage :384, GcsStore :1527, StorageMode
:192) — GCS-only, TPU-first: checkpoints ride gcsfuse MOUNT on TPU VMs
(the checkpoint/resume contract for managed jobs), datasets ride COPY.
"""
import enum
import os
import subprocess
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions, logsys, state
from skypilot_tpu.status_lib import StorageStatus
from skypilot_tpu.utils import common

logger = logsys.init_logger(__name__)


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'


class StorageHandle:
    """Pickled record in the local state DB."""

    def __init__(self, name: str, source: Optional[Union[str, List[str]]],
                 mode: StorageMode, persistent: bool):
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent


def _run_gsutil(args: List[str], check: bool = True
                ) -> subprocess.CompletedProcess:
    for base in (['gsutil', '-m'], ['gcloud', 'storage']):
        try:
            return subprocess.run(base + args, capture_output=True,
                                  text=True, check=check)
        except FileNotFoundError:
            continue
    raise exceptions.StorageError(
        'Neither gsutil nor gcloud found; cannot manage GCS buckets.')


class Storage:
    """A named bucket, optionally synced from local source(s)."""

    def __init__(self,
                 name: Optional[str] = None,
                 source: Optional[Union[str, List[str]]] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 persistent: bool = True):
        if name is None and source is None:
            raise exceptions.StorageSourceError(
                'Storage needs a name and/or a source.')
        if name is None:
            base = os.path.basename(str(source).rstrip('/'))
            name = f'skytpu-{common.get_user_hash()}-{base}'.lower()
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        self._validate_source()

    def _validate_source(self) -> None:
        from skypilot_tpu.data import data_transfer
        sources = (self.source if isinstance(self.source, list) else
                   [self.source] if self.source else [])
        for src in sources:
            if src.startswith('gs://'):
                continue
            if data_transfer.is_external_cloud_uri(src):
                # s3:// / r2:// / cos://: ingested into the GCS bucket at
                # upload time (data_transfer.transfer_to_gcs) — the TPU
                # slice itself only ever talks to GCS.  Parity:
                # sky/data/data_transfer.py:39-193.
                continue
            if not os.path.exists(os.path.expanduser(src)):
                raise exceptions.StorageSourceError(
                    f'Local source not found: {src}')

    # ------------------------------------------------------------- lifecycle

    @property
    def bucket_uri(self) -> str:
        if isinstance(self.source, str) and self.source.startswith('gs://'):
            return self.source.rstrip('/')
        return f'gs://{self.name}'

    def ensure_bucket(self) -> None:
        if isinstance(self.source, str) and self.source.startswith('gs://'):
            return  # pre-existing bucket
        res = _run_gsutil(['ls', self.bucket_uri], check=False)
        if res.returncode != 0:
            logger.info('Creating bucket %s.', self.bucket_uri)
            res = _run_gsutil(['mb', self.bucket_uri], check=False)
            if res.returncode != 0:
                raise exceptions.StorageBucketCreateError(
                    f'mb failed: {res.stderr[-500:]}')

    def upload(self) -> None:
        """Sync local source(s) into the bucket; external-cloud sources
        (s3:// / r2:// / cos://) are ingested via data_transfer."""
        from skypilot_tpu.data import data_transfer
        self.ensure_bucket()
        sources = (self.source if isinstance(self.source, list) else
                   [self.source] if self.source else [])
        for src in sources:
            if src.startswith('gs://'):
                continue
            if data_transfer.is_external_cloud_uri(src):
                try:
                    data_transfer.transfer_to_gcs(src, self.bucket_uri)
                except exceptions.StorageError as e:
                    state.add_or_update_storage(
                        self.name, self.to_handle(),
                        StorageStatus.UPLOAD_FAILED)
                    raise exceptions.StorageUploadError(str(e)) from e
                continue
            src = os.path.expanduser(src)
            dst = self.bucket_uri
            if os.path.isdir(src):
                res = _run_gsutil(['rsync', '-r', src, dst], check=False)
            else:
                res = _run_gsutil(['cp', src, dst], check=False)
            if res.returncode != 0:
                state.add_or_update_storage(self.name, self.to_handle(),
                                            StorageStatus.UPLOAD_FAILED)
                raise exceptions.StorageUploadError(
                    f'Upload of {src} failed: {res.stderr[-500:]}')
        state.add_or_update_storage(self.name, self.to_handle(),
                                    StorageStatus.READY)

    def delete(self) -> None:
        if (isinstance(self.source, str) and
                self.source.startswith('gs://')):
            logger.info('Not deleting externally-managed bucket %s.',
                        self.bucket_uri)
        else:
            res = _run_gsutil(['rm', '-r', self.bucket_uri], check=False)
            if res.returncode != 0 and 'BucketNotFound' not in res.stderr:
                raise exceptions.StorageBucketDeleteError(
                    f'Deletion of {self.bucket_uri} failed: '
                    f'{res.stderr[-500:]}')
        state.remove_storage(self.name)

    # ----------------------------------------------------------------- yaml

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        mode_str = str(config.get('mode', 'MOUNT')).upper()
        return cls(name=config.get('name'),
                   source=config.get('source'),
                   mode=StorageMode(mode_str),
                   persistent=config.get('persistent', True))

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {'name': self.name, 'mode': self.mode.value}
        if self.source is not None:
            cfg['source'] = self.source
        if not self.persistent:
            cfg['persistent'] = False
        return cfg

    def to_handle(self) -> StorageHandle:
        return StorageHandle(self.name, self.source, self.mode,
                             self.persistent)

    @classmethod
    def from_handle(cls, handle: StorageHandle) -> 'Storage':
        return cls(name=handle.name, source=handle.source, mode=handle.mode,
                   persistent=handle.persistent)
