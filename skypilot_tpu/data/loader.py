"""Tokenized-corpus data loading: memmap datasets → sharded batches.

Role parity: the reference has no data layer — its recipes bring their
own (nanoGPT's train.bin in llm/gpt-2/, HF datasets in
llm/llama-3_1-finetuning/).  Here the common pattern is a subsystem:

- ``TokenDataset`` — a flat binary file of token ids, memory-mapped
  (zero copy, scales past RAM; the nanoGPT ``.bin`` convention).
- ``token_batches`` — deterministic, seeded, epoch-shuffled [B, T+1]
  batches for the trainer's next-token objective; each epoch covers
  every complete sequence at most once per host shard (drop-last tail,
  rotated across epochs by the per-epoch permutation).
- ``shard_batch`` — host-local numpy → a global jax.Array laid out for
  the active mesh (multi-host: every process holds only its slice, the
  standard ``make_array_from_process_local_data`` pattern).
- ``write_token_file`` / ``tokenize_text_file`` — produce the binary
  from token ids or raw text + an HF tokenizer.

TPU-first notes: batches are produced host-locally and assembled into
global arrays addressed by the mesh's 'batch' sharding — no host ever
materializes the global batch, and the feed path never blocks device
dispatch (numpy slicing of a memmap is the only per-step host work).
"""
import dataclasses
import os
from typing import Dict, Iterator, Optional

import numpy as np

_MAGIC = b'SKYTPUTOK1'     # 10-byte header magic
_DTYPES = {2: np.uint16, 4: np.uint32}


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a flat token-id array as a memmap-able binary file.

    Format: 10-byte magic + 1 byte dtype width (2|4) + 5 reserved bytes,
    then little-endian token ids.  uint16 when the vocab fits (GPT-2,
    Llama-2 32k), uint32 otherwise (Llama-3 128k).
    """
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise ValueError(f'tokens must be 1-D, got shape {tokens.shape}')
    if tokens.size and tokens.min() < 0:
        raise ValueError('negative token ids')
    width = 2 if (tokens.size == 0 or tokens.max() < 2**16) else 4
    dtype = _DTYPES[width]
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        f.write(_MAGIC + bytes([width]) + b'\x00' * 5)
        le = np.dtype(dtype).newbyteorder('<')
        f.write(np.ascontiguousarray(tokens, dtype=le).tobytes())
    os.replace(tmp, path)   # atomic: readers never see a partial file


def tokenize_text_file(text_path: str, out_path: str,
                       tokenizer_name: str,
                       append_eos: bool = True) -> int:
    """Tokenize a UTF-8 text file with an HF tokenizer into a token file.
    Returns the token count."""
    from transformers import AutoTokenizer
    tok = AutoTokenizer.from_pretrained(tokenizer_name)
    with open(text_path, 'r', encoding='utf-8') as f:
        ids = tok.encode(f.read())
    if append_eos and tok.eos_token_id is not None:
        ids = list(ids) + [tok.eos_token_id]
    write_token_file(out_path, np.asarray(ids, dtype=np.int64))
    return len(ids)


class TokenDataset:
    """Memory-mapped flat token stream (read-only)."""

    def __init__(self, path: str):
        with open(path, 'rb') as f:
            header = f.read(16)
        if header[:10] != _MAGIC:
            raise ValueError(
                f'{path} is not a skytpu token file (bad magic); create '
                'it with write_token_file/tokenize_text_file')
        width = header[10]
        if width not in _DTYPES:
            raise ValueError(f'{path}: unsupported token width {width}')
        self.path = path
        self.tokens = np.memmap(path, dtype=_DTYPES[width], mode='r',
                                offset=16)

    def __len__(self) -> int:
        return int(self.tokens.shape[0])

    def num_sequences(self, seq_len: int) -> int:
        """Complete (seq_len+1)-token windows (input+shifted target)."""
        return max(0, (len(self) - 1) // seq_len)


@dataclasses.dataclass(frozen=True)
class ShardInfo:
    """This host's share of the global batch.  Defaults to the current
    jax process; pass explicitly in tests."""
    index: int = 0
    count: int = 1

    @classmethod
    def current(cls) -> 'ShardInfo':
        import jax
        return cls(index=jax.process_index(), count=jax.process_count())


def token_batches(dataset: TokenDataset, batch_size: int, seq_len: int,
                  seed: int = 0,
                  shard: Optional[ShardInfo] = None,
                  start_step: int = 0
                  ) -> Iterator[Dict[str, np.ndarray]]:
    """Seeded epoch-shuffled [batch_size, seq_len+1] batches, forever.

    - batch_size is the GLOBAL batch (sequences); this host yields its
      ``batch_size // shard.count`` rows — feed through ``shard_batch``.
      shard defaults to the current jax process (ShardInfo.current()).
    - Each epoch is a fresh permutation of all complete sequences,
      seeded by (seed, epoch): identical across hosts (so shards are
      disjoint) and across restarts.  The tail remainder
      (``n_seq % batch_size`` sequences) is dropped each epoch
      (drop-last); since the permutation differs per epoch, dropped
      sequences rotate and everything is seen across epochs.
    - start_step skips ahead deterministically — resume without
      replaying data (the trainer's restored step is the argument).
    """
    shard = shard or ShardInfo.current()
    if batch_size % shard.count:
        raise ValueError(f'global batch {batch_size} not divisible by '
                         f'host count {shard.count}')
    local_bs = batch_size // shard.count
    n_seq = dataset.num_sequences(seq_len)
    if n_seq < batch_size:
        raise ValueError(
            f'dataset has {n_seq} complete sequences of length '
            f'{seq_len + 1}; need at least one global batch '
            f'({batch_size})')
    steps_per_epoch = n_seq // batch_size
    step = start_step
    while True:
        epoch = step // steps_per_epoch
        rng = np.random.default_rng((seed, epoch))
        order = rng.permutation(n_seq)
        while step // steps_per_epoch == epoch:
            i = step % steps_per_epoch
            rows = order[i * batch_size:(i + 1) * batch_size]
            mine = rows[shard.index * local_bs:(shard.index + 1) * local_bs]
            batch = np.stack([
                np.asarray(dataset.tokens[r * seq_len:
                                          r * seq_len + seq_len + 1])
                for r in mine
            ]).astype(np.int32)
            yield {'tokens': batch}
            step += 1


def shard_batch(batch: Dict[str, np.ndarray], mesh) -> Dict:
    """Host-local rows → global jax.Arrays sharded over the mesh's batch
    axes.  Single-process: a plain device_put with the batch sharding."""
    import jax

    from skypilot_tpu.parallel import mesh as mesh_lib
    sharding = mesh_lib.named_sharding(mesh, 'batch', None)

    def to_global(x):
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return {k: to_global(v) for k, v in batch.items()}
