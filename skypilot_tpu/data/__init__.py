"""Data/storage layer (parity: sky/data/), plus the token-corpus loading
subsystem (loader.py — beyond the reference, which delegates data loading
to each recipe)."""
