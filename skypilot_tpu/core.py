"""Core ops API: status / start / stop / down / autostop / queue / cancel /
logs / cost_report / storage.

Parity: sky/core.py:41-899.
"""
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import usage
from skypilot_tpu import backend_utils, exceptions, logsys, state
from skypilot_tpu.backends import SliceBackend
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.utils import common, ux

logger = logsys.init_logger(__name__)


@usage.entrypoint('status')
def status(cluster_names: Optional[Union[str, List[str]]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records (optionally reconciled against the cloud)."""
    if isinstance(cluster_names, str):
        cluster_names = [cluster_names]
    return backend_utils.get_clusters(refresh=refresh,
                                      cluster_names=cluster_names)


@usage.entrypoint('start')
def start(cluster_name: str, retry_until_up: bool = False) -> None:
    """Restart a STOPPED cluster (controller VMs; TPU slices cannot stop).
    Parity: sky/core.py start()."""
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    backend_utils.check_owner_identity(cluster_name)
    handle = record['handle']
    if handle.launched_resources.is_tpu:
        raise exceptions.NotSupportedError(
            'TPU slices cannot be stopped/started; relaunch instead.')
    from skypilot_tpu import provision
    from skypilot_tpu.provision import provisioner
    from skypilot_tpu.clouds import Cloud
    resources = handle.launched_resources
    cloud = Cloud.from_name(resources.cloud)
    config = cloud.make_deploy_variables(resources, cluster_name,
                                         resources.region, resources.zone)
    provision.run_instances(resources.cloud, resources.region,
                            resources.zone, cluster_name, config)
    provision.wait_instances(resources.cloud, resources.region,
                             resources.zone, cluster_name)
    info = provision.get_cluster_info(resources.cloud, resources.region,
                                      resources.zone, cluster_name)
    import os
    log_path = os.path.join(common.logs_dir(), cluster_name, 'start.log')
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    provisioner.post_provision_runtime_setup(cluster_name, info, log_path)
    state.add_or_update_cluster(cluster_name, handle, None, ready=True,
                                is_launch=False)


@usage.entrypoint('stop')
def stop(cluster_name: str, purge: bool = False) -> None:
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    backend_utils.check_owner_identity(cluster_name)
    SliceBackend().teardown(record['handle'], terminate=False, purge=purge)


@usage.entrypoint('down')
def down(cluster_name: str, purge: bool = False) -> None:
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    backend_utils.check_owner_identity(cluster_name)
    SliceBackend().teardown(record['handle'], terminate=True, purge=purge)


@usage.entrypoint('autostop')
def autostop(cluster_name: str, idle_minutes: int,
             down_after_idle: bool = False) -> None:
    """idle_minutes < 0 cancels autostop.  TPU slices require down=True."""
    handle = backend_utils.check_cluster_available(cluster_name)
    SliceBackend().set_autostop(handle, idle_minutes, down=down_after_idle)
    if idle_minutes >= 0:
        what = 'autodown' if down_after_idle else 'autostop'
        logger.info('%s %s set: %d min idle.', ux.ok('[ok]'), what,
                    idle_minutes)
    else:
        logger.info('%s autostop cancelled.', ux.ok('[ok]'))


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    handle = backend_utils.check_cluster_available(cluster_name)
    return SliceBackend().get_job_queue(handle)


@usage.entrypoint('cancel')
def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    handle = backend_utils.check_cluster_available(cluster_name)
    if not all_jobs and not job_ids:
        raise exceptions.JobNotFoundError(
            'Specify job ids or all_jobs=True.')
    return SliceBackend().cancel_jobs(handle,
                                      None if all_jobs else job_ids)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True) -> int:
    handle = backend_utils.check_cluster_available(cluster_name)
    return SliceBackend().tail_logs(handle, job_id, follow=follow)


def download_logs(cluster_name: str,
                  job_id: Optional[int] = None) -> str:
    handle = backend_utils.check_cluster_available(cluster_name)
    return SliceBackend().sync_down_logs(handle, job_id)


def job_status(cluster_name: str,
               job_id: Optional[int] = None) -> Dict[str, Any]:
    handle = backend_utils.check_cluster_available(cluster_name)
    return SliceBackend().get_job_status(handle, job_id)


@usage.entrypoint('cost_report')
def cost_report() -> List[Dict[str, Any]]:
    """Per-cluster accumulated cost from usage intervals.
    Parity: sky/core.py cost_report + status_utils."""
    out = []
    for rec in state.get_cluster_history():
        launched = rec['launched_resources']
        if launched is None:
            continue
        total_seconds = 0.0
        now = time.time()
        for start_t, end_t in rec['usage_intervals']:
            total_seconds += (end_t or now) - start_t
        try:
            cost = launched.get_cost(total_seconds) * (rec['num_nodes'] or 1)
        except exceptions.SkyTpuError:
            cost = 0.0
        out.append({
            'name': rec['name'],
            'resources': launched,
            'duration_seconds': total_seconds,
            'cost': cost,
        })
    return out


def storage_ls() -> List[Dict[str, Any]]:
    return state.get_storage()


def storage_delete(name: str) -> None:
    handle = state.get_storage_handle(name)
    if handle is None:
        raise exceptions.StorageError(f'Storage {name!r} not found.')
    from skypilot_tpu.data import storage as storage_lib
    store = storage_lib.Storage.from_handle(handle)
    store.delete()
