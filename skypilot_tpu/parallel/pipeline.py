"""GPipe pipeline parallelism over the mesh 'stage' axis.

The reference has no framework-level pipeline parallelism (SURVEY.md
§2.9: its "pipeline" example is DAG stage-chaining, not micro-batch PP).
Here it is a mesh axis: layers are partitioned into S stages, each stage's
parameters live only on its stage's devices (leading stacked dim sharded
over 'stage'), and activations hop stage→stage+1 with `ppermute` while
M microbatches flow through the classic GPipe schedule (M + S - 1 ticks,
bubble fraction (S-1)/(M+S-1)).

Everything runs inside one `shard_map` under jit: the backward schedule
falls out of reverse-mode AD (ppermute's transpose is the reverse hop),
and `jax.checkpoint` around the stage body keeps activation memory at
one microbatch per stage.

Composability: the 'stage' axis is orthogonal to data/fsdp/seq/tensor —
inside a stage, tensors keep their logical shardings on the remaining
axes.  Put 'stage' (and 'data') across DCN when spanning slices: one
activation hop per microbatch is the cheapest cross-slice traffic
pattern.
"""
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.parallel import mesh as mesh_lib

P = jax.sharding.PartitionSpec


def pipeline_degree(mesh: Optional[jax.sharding.Mesh] = None) -> int:
    mesh = mesh if mesh is not None else _active_mesh()
    if mesh is None or 'stage' not in mesh.shape:
        return 1
    return mesh.shape['stage']


def _active_mesh() -> Optional[jax.sharding.Mesh]:
    try:
        from jax._src import mesh as jmesh
        m = jmesh.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        return None
    return None if m.empty else m


def pipeline(stage_fn: Callable[[Any, jax.Array, Any], jax.Array],
             stage_params: Any,
             microbatches: jax.Array,
             consts: Any,
             mesh: jax.sharding.Mesh,
             axis_name: str = 'stage') -> jax.Array:
    """Run microbatches through S pipeline stages.

    Args:
      stage_fn: (params_for_one_stage, x, consts) -> y, with y.shape ==
        x.shape (a chunk of transformer layers).
      stage_params: pytree whose every leaf has leading dim S (stacked
        per-stage weights); sharded over 'stage'.
      microbatches: [M, mb, ...] stage-0 inputs.  The per-microbatch
        batch dim may additionally be sharded over data/fsdp.
      consts: pytree broadcast to every stage invocation (e.g. positions).
      mesh: the device mesh (must contain `axis_name`).

    Returns [M, mb, ...] last-stage outputs (replicated over 'stage').
    """
    num_stages = mesh.shape[axis_name]
    num_micro = microbatches.shape[0]
    if num_micro < num_stages:
        raise ValueError(
            f'need microbatches ({num_micro}) >= stages ({num_stages}) '
            'to fill the pipeline')

    def run(params, mbs, consts):
        # Leaves arrive as [1, ...] slices of the stacked stage dim.
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        s = lax.axis_index(axis_name)
        body = jax.checkpoint(
            lambda p, x, c: stage_fn(p, x, c))
        perm = [(i, i + 1) for i in range(num_stages - 1)]

        buf = jnp.zeros_like(mbs[0])
        outputs = jnp.zeros_like(mbs)
        for t in range(num_micro + num_stages - 1):
            mb_idx = min(t, num_micro - 1)
            x0 = mbs[mb_idx]
            x = jnp.where(s == 0, x0, buf)
            y = body(params, x, consts)
            out_idx = max(t - (num_stages - 1), 0)
            take = (s == num_stages - 1) & (t >= num_stages - 1)
            outputs = outputs.at[out_idx].set(
                jnp.where(take, y, outputs[out_idx]))
            if t != num_micro + num_stages - 2:
                buf = lax.ppermute(y, axis_name, perm)
        # Broadcast the last stage's outputs to every stage so downstream
        # (head/loss) math is replicated over 'stage'.
        outputs = lax.psum(
            jnp.where(s == num_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    batch_axes = ('data', 'fsdp')
    x_spec = P(None, batch_axes)           # [M, mb, ...]: mb data-sharded
    return jax.shard_map(
        run, mesh=mesh,
        in_specs=(P(axis_name), x_spec, P()),
        out_specs=x_spec)(stage_params, microbatches, consts)


class PipelinedLM:
    """A Llama-family LM with its decoder stack pipelined over 'stage'.

    Parameters:
      {'embed': [V, H] (replicated over stage),
       'stages': stacked per-stage DecoderLayer params ([S, ...] leaves),
       'final_norm': RMSNorm scale}
    Embedding and the (tied) LM head are computed replicated on every
    stage — they are O(1%) of the FLOPs; the layer stack is what
    pipelines.

    Reference contrast: llm/gpt-2/gpt2-pipeline.yaml chains whole TASKS
    (data stage -> train stage); this is true micro-batch model
    parallelism.
    """

    def __init__(self, config, num_stages: int, num_microbatches: int):
        from skypilot_tpu.models.llama import DecoderLayer
        if config.num_layers % num_stages:
            raise ValueError(
                f'num_layers {config.num_layers} must divide evenly into '
                f'{num_stages} stages')
        self.config = config
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.layers_per_stage = config.num_layers // num_stages

        import flax.linen as nn

        cfg = config
        layers_per_stage = self.layers_per_stage

        class Stage(nn.Module):

            @nn.compact
            def __call__(self, x, positions):
                for i in range(layers_per_stage):
                    x = DecoderLayer(cfg, name=f'layer_{i}')(x, positions)
                return x

        self._stage_module = Stage()

    def init(self, rng: jax.Array, sample_tokens: jax.Array) -> Any:
        cfg = self.config
        h = cfg.hidden_size
        rng_e, rng_s, rng_n = jax.random.split(rng, 3)
        embed = jax.random.normal(rng_e, (cfg.vocab_size, h),
                                  jnp.float32) * 0.02
        x = jnp.zeros((1, sample_tokens.shape[1], h), cfg.dtype)
        positions = jnp.zeros((1, sample_tokens.shape[1]), jnp.int32)

        def init_one(key):
            return self._stage_module.init(key, x, positions)['params']

        stage_keys = jax.random.split(rng_s, self.num_stages)
        stages = jax.vmap(init_one)(stage_keys)
        return {
            'embed': embed,
            'stages': stages,
            'final_norm': jnp.zeros((h,), jnp.float32),
        }

    def apply(self, params: Any, tokens: jax.Array,
              mesh: Optional[jax.sharding.Mesh] = None) -> jax.Array:
        """tokens [B, S] -> logits [B, S, V] (tied embeddings)."""
        from skypilot_tpu.models.llama import rmsnorm
        cfg = self.config
        mesh = mesh if mesh is not None else _active_mesh()
        assert mesh is not None, 'PipelinedLM needs an active mesh'
        b, seq = tokens.shape
        m = self.num_microbatches
        if b % m:
            raise ValueError(f'batch {b} must divide microbatches {m}')
        # [1, seq]: broadcasts against any local batch size inside the
        # shard_map (rope broadcasts the batch dim), so it can ride the
        # replicated `consts` slot regardless of data sharding.
        positions = jnp.arange(seq)[None]
        x = params['embed'].astype(cfg.dtype)[tokens]
        mbs = x.reshape(m, b // m, seq, cfg.hidden_size)

        def stage_fn(stage_params, xmb, consts):
            return self._stage_module.apply({'params': stage_params}, xmb,
                                            consts)

        out = pipeline(stage_fn, params['stages'], mbs, positions, mesh)
        out = out.reshape(b, seq, cfg.hidden_size)
        out = rmsnorm(out, params['final_norm'], cfg.norm_eps)
        return out.astype(jnp.float32) @ \
            params['embed'].astype(jnp.float32).T


def make_pipelined_train_step(model: PipelinedLM,
                              mesh: jax.sharding.Mesh,
                              learning_rate: float = 3e-4):
    """Minimal adamw train step for a PipelinedLM (used by tests and the
    multichip dryrun's pp configuration)."""
    import optax

    tx = optax.adamw(learning_rate)

    def init_state(rng, sample_tokens):
        params = model.init(rng, sample_tokens)
        return params, tx.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        def loss_fn(p):
            logits = model.apply(p, inputs, mesh)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    return init_state, step
