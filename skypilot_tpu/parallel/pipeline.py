"""GPipe pipeline parallelism over the mesh 'stage' axis.

The reference has no framework-level pipeline parallelism (SURVEY.md
§2.9: its "pipeline" example is DAG stage-chaining, not micro-batch PP).
Here it is a mesh axis: layers are partitioned into S stages, each stage's
parameters live only on its stage's devices (leading stacked dim sharded
over 'stage'), and activations hop stage→stage+1 with `ppermute` while
M microbatches flow through the classic GPipe schedule (M + S - 1 ticks,
bubble fraction (S-1)/(M+S-1)).

Everything runs inside one `shard_map` under jit: the backward schedule
falls out of reverse-mode AD (ppermute's transpose is the reverse hop),
and `jax.checkpoint` around the stage body keeps activation memory at
one microbatch per stage.

Composability: the 'stage' axis is orthogonal to data/fsdp/seq/tensor —
inside a stage, tensors keep their logical shardings on the remaining
axes.  Put 'stage' (and 'data') across DCN when spanning slices: one
activation hop per microbatch is the cheapest cross-slice traffic
pattern.
"""
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.parallel import mesh as mesh_lib

P = jax.sharding.PartitionSpec


def pipeline_degree(mesh: Optional[jax.sharding.Mesh] = None) -> int:
    mesh = mesh if mesh is not None else _active_mesh()
    if mesh is None or 'stage' not in mesh.shape:
        return 1
    return mesh.shape['stage']


def _active_mesh() -> Optional[jax.sharding.Mesh]:
    try:
        from jax._src import mesh as jmesh
        m = jmesh.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        return None
    return None if m.empty else m


def pipeline(stage_fn: Callable[[Any, jax.Array, Any], jax.Array],
             stage_params: Any,
             microbatches: jax.Array,
             consts: Any,
             mesh: jax.sharding.Mesh,
             axis_name: str = 'stage') -> jax.Array:
    """Run microbatches through S pipeline stages.

    Args:
      stage_fn: (params_for_one_stage, x, consts) -> y, with y.shape ==
        x.shape (a chunk of transformer layers).
      stage_params: pytree whose every leaf has leading dim S (stacked
        per-stage weights); sharded over 'stage'.
      microbatches: [M, mb, ...] stage-0 inputs.  The per-microbatch
        batch dim may additionally be sharded over data/fsdp.
      consts: pytree broadcast to every stage invocation (e.g. positions).
      mesh: the device mesh (must contain `axis_name`).

    Returns [M, mb, ...] last-stage outputs (replicated over 'stage').
    """
    num_stages = mesh.shape[axis_name]
    num_micro = microbatches.shape[0]
    if num_micro < num_stages:
        raise ValueError(
            f'need microbatches ({num_micro}) >= stages ({num_stages}) '
            'to fill the pipeline')

    def run(params, mbs, consts):
        # Leaves arrive as [1, ...] slices of the stacked stage dim.
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        s = lax.axis_index(axis_name)
        body = jax.checkpoint(
            lambda p, x, c: stage_fn(p, x, c))
        perm = [(i, i + 1) for i in range(num_stages - 1)]

        buf = jnp.zeros_like(mbs[0])
        outputs = jnp.zeros_like(mbs)
        for t in range(num_micro + num_stages - 1):
            mb_idx = min(t, num_micro - 1)
            x0 = mbs[mb_idx]
            x = jnp.where(s == 0, x0, buf)
            y = body(params, x, consts)
            out_idx = max(t - (num_stages - 1), 0)
            take = (s == num_stages - 1) & (t >= num_stages - 1)
            outputs = outputs.at[out_idx].set(
                jnp.where(take, y, outputs[out_idx]))
            if t != num_micro + num_stages - 2:
                buf = lax.ppermute(y, axis_name, perm)
        # Broadcast the last stage's outputs to every stage so downstream
        # (head/loss) math is replicated over 'stage'.
        outputs = lax.psum(
            jnp.where(s == num_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    batch_axes = ('data', 'fsdp')
    x_spec = P(None, batch_axes)           # [M, mb, ...]: mb data-sharded
    return jax.shard_map(
        run, mesh=mesh,
        in_specs=(P(axis_name), x_spec, P()),
        out_specs=x_spec)(stage_params, microbatches, consts)


def _stack_layer_params(params: Any, num_layers: int,
                        num_stages: int) -> Any:
    """Stack the per-layer subtrees ('layer_0'..'layer_{L-1}') into
    [S, L/S, ...] leaves (stage-major).  Pure restructuring: gradients
    flow back through the stack to the original leaves, so the stored
    param tree — and therefore init, checkpoints, and the optimizer —
    stays IDENTICAL to the non-pipelined layout."""
    lps = num_layers // num_stages
    layer_trees = [params[f'layer_{i}'] for i in range(num_layers)]

    def stack(*leaves):
        return jnp.stack(leaves).reshape(num_stages, lps,
                                         *leaves[0].shape)

    return jax.tree.map(stack, *layer_trees)


def make_pipelined_apply(config: Any, mesh: jax.sharding.Mesh,
                         num_microbatches: Optional[int] = None
                         ) -> Callable:
    """A `state.apply_fn`-compatible forward that pipelines the decoder
    stack over the mesh 'stage' axis (GPipe schedule via `pipeline`).

    This is how TrainConfig(mesh=MeshSpec(stage=S, ...)) trains through
    the ordinary Trainer entry (VERDICT r1 #4): the param tree is the
    standard per-layer flax tree — created by `create_sharded_state`,
    checkpointed by orbax, updated by the shared optimizer — and only
    the jit'd forward restructures it: layer subtrees stack into
    [S, L/S, ...] leaves constrained to 'stage' (each stage's devices
    materialize only their own layers inside the step), embedding/norm/
    head stay replicated over 'stage' (O(1%) of FLOPs).

    Signature matches flax Module.apply for the trainer's call sites:
    ``fn({'params': p}, tokens, hidden_only=..., mutable=...)``.
    """
    import flax.linen as nn

    from skypilot_tpu.models.llama import (DecoderLayer, LlamaConfig,
                                           rmsnorm)
    if not isinstance(config, LlamaConfig):
        raise ValueError(
            'pipeline-parallel training currently supports llama-family '
            f'models; got {type(config).__name__}')
    num_stages = mesh.shape['stage']
    if config.num_layers % num_stages:
        raise ValueError(
            f'num_layers {config.num_layers} must divide evenly into '
            f'{num_stages} stages')
    lps = config.num_layers // num_stages
    m = num_microbatches or num_stages
    layer_mod = DecoderLayer(config)

    def stage_fn(stage_params, x, positions):
        # Inside shard_map every mesh axis is manual: the model's
        # logical-axis constraints must resolve to no-ops (empty rules),
        # exactly as in single-device execution of a local shard.
        with nn.logical_axis_rules(()):
            for j in range(lps):
                p = jax.tree.map(lambda a: a[j], stage_params)
                x = layer_mod.apply({'params': p}, x, positions)
        return x

    def apply(variables, tokens, hidden_only=False, mutable=None):
        # Accept boxed (fresh model.init output) or unboxed trees alike.
        params = nn.meta.unbox(variables['params'])
        b, seq = tokens.shape
        if b % m:
            raise ValueError(
                f'batch {b} must divide into {m} pipeline microbatches')
        positions = jnp.arange(seq)[None]
        x = params['embedding'].astype(config.dtype)[tokens]
        stacked = _stack_layer_params(params, config.num_layers,
                                      num_stages)
        stacked = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(
                a, jax.sharding.NamedSharding(mesh, P('stage'))), stacked)
        mbs = x.reshape(m, b // m, seq, config.hidden_size)
        out = pipeline(stage_fn, stacked, mbs, positions, mesh)
        x = out.reshape(b, seq, config.hidden_size)
        x = rmsnorm(x, params['final_norm']['scale'], config.norm_eps)
        if hidden_only:
            res = x
        elif config.tie_embeddings:
            res = x.astype(jnp.float32) @ \
                params['embedding'].astype(jnp.float32).T
        else:
            res = x.astype(jnp.float32) @ \
                params['lm_head']['kernel'].astype(jnp.float32)
        if mutable is not None:
            return res, {}
        return res

    return apply
