"""Mesh parallelism: device meshes, logical sharding rules, collectives.

The reference delegates all parallelism to workload recipes over NCCL
(SURVEY.md §2.9); here it is a first-class subsystem: jax.sharding over an
ICI/DCN-aware Mesh, with XLA emitting the collectives.
"""
from skypilot_tpu.parallel.mesh import (MeshSpec,
                                        initialize_distributed_from_env,
                                        make_mesh, logical_axis_rules,
                                        mesh_context, tp_mesh)

__all__ = ['MeshSpec', 'initialize_distributed_from_env', 'make_mesh',
           'logical_axis_rules', 'mesh_context', 'tp_mesh']
