"""Device meshes + logical axis rules (the sharding vocabulary).

Design: a 5-axis mesh ('data', 'fsdp', 'seq', 'tensor', 'stage')
covering the parallelism strategies the reference ships as NCCL recipes
(SURVEY.md §2.9):

  data   — pure data parallel; gradients all-reduce (DCN-friendly: this is
           the axis to span slices with, megascale-style).
  fsdp   — parameter/optimizer sharding (ZeRO-3 analog); params
           all-gathered per layer, grads reduce-scattered. Rides ICI.
  seq    — sequence/context parallelism (ring attention axis). Rides ICI
           neighbors.
  tensor — Megatron-style tensor parallel for mlp/heads. Innermost, needs
           the fastest ICI.
  stage  — GPipe pipeline stages (parallel/pipeline.py): activations hop
           stage->stage+1 with ppermute; never referenced by logical
           axis rules (stage parallelism partitions LAYERS, not tensors).
           Outermost: stage hops are infrequent (once per microbatch) so
           this is the axis to span DCN/multi-slice with, alongside
           'data'.

Model code never names mesh axes: it uses LOGICAL axes ('batch', 'embed',
'mlp', 'heads', ...) mapped here — swapping strategies is a rules edit,
not a model edit.
"""
import contextlib
import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

MESH_AXES = ('stage', 'data', 'fsdp', 'seq', 'tensor')

# Logical axis -> mesh axis (or tuple: sharded over both, or None).
_BASE_RULES: List[Tuple[str, object]] = [
    ('batch', ('data', 'fsdp')),
    ('activation_batch', ('data', 'fsdp')),
    ('activation_seq', 'seq'),
    ('activation_embed', None),
    ('activation_heads', 'tensor'),
    ('activation_kv', 'tensor'),
    ('activation_mlp', 'tensor'),
    ('embed', 'fsdp'),        # weight embed dim: FSDP-sharded
    ('mlp', 'tensor'),
    ('heads', 'tensor'),
    ('kv_heads', 'tensor'),
    ('qkv_embed', None),
    ('vocab', 'tensor'),
    ('vocab_table', 'fsdp'),
    ('embed_table', 'tensor'),
    ('expert', 'tensor'),
    ('norm', None),
]


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Parallelism degrees.  Product must equal the device count."""
    data: int = 1
    fsdp: int = 1
    seq: int = 1
    tensor: int = 1
    stage: int = 1

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.stage, self.data, self.fsdp, self.seq, self.tensor)

    @property
    def num_devices(self) -> int:
        return self.data * self.fsdp * self.seq * self.tensor * self.stage

    @classmethod
    def fsdp_only(cls, n: int) -> 'MeshSpec':
        return cls(fsdp=n)

    @classmethod
    def auto(cls, n: int) -> 'MeshSpec':
        """Sensible single-slice default: FSDP over all chips."""
        return cls(fsdp=n)


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """Build a Mesh laying axes out so the innermost ('tensor') axis maps
    to the closest devices in the default device order (on TPU, device
    order follows the ICI torus — adjacent ids are physical neighbors, so
    inner axes get the fastest links).

    Multi-slice note: when spanning slices (jax.distributed over DCN), put
    the slice dimension on 'data' — gradient all-reduce is the only
    DCN-crossing collective in the FSDP+TP recipe.
    """
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec.auto(len(devices))
    if spec.num_devices != len(devices):
        raise ValueError(
            f'MeshSpec {spec.shape} needs {spec.num_devices} devices, got '
            f'{len(devices)}.')
    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_device_mesh(spec.shape, devices=devices)
    except (ValueError, AssertionError):
        arr = np.array(devices).reshape(spec.shape)
    return jax.sharding.Mesh(arr, MESH_AXES)


def logical_axis_rules(extra: Optional[List[Tuple[str, object]]] = None):
    """Base rules with optional overrides.  Resolution is FIRST-match (flax
    semantics), so user overrides are prepended."""
    rules = list(_BASE_RULES)
    if extra:
        rules = list(extra) + rules
    return rules


@contextlib.contextmanager
def mesh_context(mesh: jax.sharding.Mesh,
                 rules: Optional[List[Tuple[str, object]]] = None):
    """Activate mesh + logical rules for flax with_logical_* APIs."""
    import flax.linen as nn
    with mesh, nn.logical_axis_rules(logical_axis_rules(rules)):
        yield


def named_sharding(mesh: jax.sharding.Mesh,
                   *logical_axes: Optional[str]) -> jax.sharding.NamedSharding:
    """NamedSharding from logical axis names.  First-match resolution, same
    precedence as flax's rule lookup."""

    def resolve(ax: Optional[str]):
        if ax is None:
            return None
        for name, mesh_ax in logical_axis_rules():
            if name == ax:
                return mesh_ax
        return None

    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(*(resolve(a) for a in logical_axes)))


def tp_mesh(tensor_parallel: int,
            devices: Optional[Sequence] = None
            ) -> Optional[jax.sharding.Mesh]:
    """The ONE way a serving replica builds its tensor-parallel mesh —
    shared by the HTTP server entrypoint, the chaos harness and the
    tests so every TP replica in a fleet agrees on device order (the
    first N local devices: innermost axis on the fastest ICI links).

    Returns None for degree <= 1: an unsharded engine takes mesh=None,
    so data-parallel and tensor-parallel replicas flow through one
    code path and differ only in this return value.
    """
    if tensor_parallel is None or tensor_parallel <= 1:
        return None
    devs = list(devices if devices is not None else jax.devices())
    if tensor_parallel > len(devs):
        raise ValueError(
            f'tensor_parallel {tensor_parallel} exceeds the {len(devs)} '
            'visible device(s); a mesh needs one chip per shard')
    return make_mesh(MeshSpec(tensor=tensor_parallel),
                     devices=devs[:tensor_parallel])


def host_local_device_count() -> int:
    return jax.local_device_count()


def initialize_distributed_from_env() -> bool:
    """Call jax.distributed.initialize() from the env the podlet driver
    exports (SKYTPU_COORDINATOR_ADDRESS / PROCESS_ID / NUM_PROCESSES).
    Returns True if multi-process init happened.

    Parity role: the reference's recipes hand-build torch.distributed
    rendezvous from SKYPILOT_NODE_RANK/IPS (examples/
    resnet_distributed_torch.yaml:19-26); here it is one call.
    """
    import os
    coord = os.environ.get('SKYTPU_COORDINATOR_ADDRESS')
    nproc = int(os.environ.get('SKYTPU_NUM_PROCESSES', '1'))
    if coord is None or nproc <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=int(os.environ.get('SKYTPU_PROCESS_ID', '0')),
    )
    return True
