"""skypilot_tpu: a TPU-native sky-computing framework.

Declarative Task/Resources API + cost optimizer + TPU pod-slice gang
provisioning on GCP with zone/slice failover, an on-slice job queue
("podlet"), managed (preemptible) jobs with checkpoint/resume recovery, and
an autoscaled serving plane — plus a JAX/XLA-native compute stack (models,
pallas ops, mesh parallelism, training and serving engines).

Public surface parity: sky/__init__.py:139-199.
"""
__version__ = '0.1.0'

from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

__all__ = [
    'Dag',
    'Resources',
    'Task',
    '__version__',
]


def __getattr__(name):
    """Lazy re-exports: keep `import skypilot_tpu` fast (no jax/pandas)."""
    _lazy = {
        # execution
        'launch': ('skypilot_tpu.execution', 'launch'),
        'exec': ('skypilot_tpu.execution', 'exec_'),
        'optimize': ('skypilot_tpu.optimizer', 'optimize'),
        # core ops
        'status': ('skypilot_tpu.core', 'status'),
        'start': ('skypilot_tpu.core', 'start'),
        'stop': ('skypilot_tpu.core', 'stop'),
        'down': ('skypilot_tpu.core', 'down'),
        'autostop': ('skypilot_tpu.core', 'autostop'),
        'queue': ('skypilot_tpu.core', 'queue'),
        'cancel': ('skypilot_tpu.core', 'cancel'),
        'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
        'download_logs': ('skypilot_tpu.core', 'download_logs'),
        'cost_report': ('skypilot_tpu.core', 'cost_report'),
        'storage_ls': ('skypilot_tpu.core', 'storage_ls'),
        'storage_delete': ('skypilot_tpu.core', 'storage_delete'),
        # planes
        'jobs': ('skypilot_tpu', 'jobs'),
        'serve': ('skypilot_tpu', 'serve'),
        'bench': ('skypilot_tpu', 'bench'),
        # optimizer enum
        'OptimizeTarget': ('skypilot_tpu.optimizer', 'OptimizeTarget'),
        'ClusterStatus': ('skypilot_tpu.status_lib', 'ClusterStatus'),
    }
    if name in _lazy:
        import importlib
        module, attr = _lazy[name]
        mod = importlib.import_module(module)
        if attr == name and module == 'skypilot_tpu':
            return importlib.import_module(f'skypilot_tpu.{name}')
        return getattr(mod, attr)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
