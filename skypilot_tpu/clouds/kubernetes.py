"""Kubernetes cloud: TPUs on GKE (pods-as-hosts).

Parity: sky/clouds/kubernetes.py + sky/provision/kubernetes/ (the
reference's pods-as-nodes provider, instance.py:921, utils.py:2138) —
TPU-first: the unit is a GKE TPU *podslice*.  GKE exposes TPU capacity
through node pools labeled with `cloud.google.com/gke-tpu-accelerator`
and `cloud.google.com/gke-tpu-topology`; a workload claims chips by
requesting the `google.com/tpu` extended resource with matching
nodeSelectors.  This cloud maps the framework's accelerator strings
(`tpu-v5e-8`, ...) onto those selectors; the provision impl
(provision/kubernetes) creates one pod per TPU host plus a headless
service for stable pod DNS.

Opt-in like the `local` cloud: never chosen by the optimizer unless the
task pins `cloud: kubernetes` (most users have no kubeconfig).
Cluster-internal capacity is priced at $0 (parity: the reference treats
self-hosted k8s as free and lets the optimizer prefer it).
"""
import shutil
import subprocess
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.clouds.cloud import Cloud, CloudCapability

# Framework TPU generation -> GKE accelerator label value.
# v5e/v6e node pools carry the catalog's 2D chip grid as their topology
# label; v4/v5p are 3D tori whose label is derived (below).
_GKE_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}
# Generations whose GKE topology label is the 3D chip torus, not the 2D
# host grid the catalog records.
_3D_TOPOLOGY_GENERATIONS = ('v4', 'v5p')


def _topology_3d(chips: int) -> str:
    """Chip count -> GKE 3D topology label for v4/v5p tori.

    GCP's published shapes (ct4p/ct5p node pools: 2x2x1, 2x2x2, 2x2x4,
    2x4x4, 4x4x4, 4x4x8, ...) are the balanced power-of-two
    factorization: grow the smallest dimension by 2 until the product
    reaches the chip count, then print ascending."""
    if chips < 1 or chips & (chips - 1):
        raise exceptions.InvalidResourcesError(
            f'cannot derive a 3D torus topology for {chips} chips '
            '(not a power of two)')
    dims = [1, 1, 1]
    while dims[0] * dims[1] * dims[2] < chips:
        dims.sort()
        dims[0] *= 2
    # GCP prints ascending with any 1s trailing: 2x2x1, 2x2x4, 2x4x4.
    dims.sort()
    dims = [d for d in dims if d > 1] + [d for d in dims if d == 1]
    return 'x'.join(str(d) for d in dims)


def gke_selectors(accelerator: Optional[str]) -> Dict[str, str]:
    """accelerator string -> GKE nodeSelector labels (empty for CPU).
    The slice shape comes from the catalog (the same physical shape the
    TPU-VM path uses); the accelerator label is mapped per generation
    and v4/v5p topologies are lifted to their 3D chip-torus form."""
    if not accelerator:
        return {}
    from skypilot_tpu import catalog
    info = catalog.get_slice_info(accelerator)   # raises on unknown
    gke_acc = _GKE_ACCELERATOR.get(info.generation)
    if gke_acc is None:
        raise exceptions.InvalidResourcesError(
            f'no GKE podslice mapping for {accelerator!r} (generation '
            f'{info.generation}); kubernetes currently supports '
            f'{sorted(_GKE_ACCELERATOR)} — use cloud: gcp for the rest')
    topology = (_topology_3d(info.chips)
                if info.generation in _3D_TOPOLOGY_GENERATIONS
                else info.topology)
    return {
        'cloud.google.com/gke-tpu-accelerator': gke_acc,
        'cloud.google.com/gke-tpu-topology': topology,
    }


class Kubernetes(Cloud):
    NAME = 'kubernetes'

    def capabilities(self) -> set:
        # No STOP: pods terminate, they don't stop.  No AUTOSTOP:
        # autodown runs ON the head host, and pods carry no kubectl/
        # RBAC to delete themselves — advertising it would leak idle
        # TPU pods.  SPOT maps to GKE spot node pools (the scheduler
        # lands on them via the `cloud.google.com/gke-spot` selector).
        return {
            CloudCapability.SPOT,
            CloudCapability.MULTI_HOST,
            CloudCapability.HOST_CONTROLLERS,
            CloudCapability.OPEN_PORTS,
        }

    def get_feasible_resources(self, resources) -> List[Any]:
        if resources.cloud not in ('kubernetes', 'k8s'):
            return []   # opt-in
        if resources.accelerator:
            gke_selectors(resources.accelerator)   # validate mapping
        # Multi-host podslices (num_hosts > 1) are supported: one pod
        # per TPU host, gang-driven over the podlet agent on pod IPs
        # (podlet/agent.py); GKE schedules the podslice's pods onto the
        # matching node pool atomically.
        return [resources]

    def region_zones_for(self, resources) -> Iterator[Tuple[str,
                                                            Optional[str]]]:
        # One "region" per kube-context; the active context is the
        # deploy target (parity: the reference's allowed_contexts).
        yield self.current_context() or 'in-cluster', None

    def hourly_cost(self, resources) -> float:
        return 0.0   # self-hosted cluster capacity

    def make_deploy_variables(self, resources, cluster_name: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        num_hosts = resources.num_hosts if resources.is_tpu else 1
        return {
            'cluster_name': cluster_name,
            'node_kind': 'kubernetes',
            'context': region,
            'num_hosts': num_hosts,
            'num_slices': getattr(resources, 'num_slices', 1),
            'chips_per_host': resources.chips_per_host,
            'accelerator': resources.accelerator,
            'node_selectors': gke_selectors(resources.accelerator),
            'use_spot': resources.use_spot,
        }

    @staticmethod
    def current_context() -> Optional[str]:
        if not shutil.which('kubectl'):
            return None
        try:
            res = subprocess.run(
                ['kubectl', 'config', 'current-context'],
                capture_output=True, text=True, timeout=10)
        except (subprocess.TimeoutExpired, OSError):
            return None
        return res.stdout.strip() if res.returncode == 0 else None

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if not shutil.which('kubectl'):
            return False, 'kubectl not found on PATH'
        ctx = self.current_context()
        if not ctx:
            return False, 'no current kube-context (kubectl config ...)'
        return True, None

    def get_active_user_identity(self) -> Optional[List[str]]:
        ctx = self.current_context()
        return [ctx] if ctx else None
