"""Kubernetes cloud: TPUs on GKE (pods-as-hosts).

Parity: sky/clouds/kubernetes.py + sky/provision/kubernetes/ (the
reference's pods-as-nodes provider, instance.py:921, utils.py:2138) —
TPU-first: the unit is a GKE TPU *podslice*.  GKE exposes TPU capacity
through node pools labeled with `cloud.google.com/gke-tpu-accelerator`
and `cloud.google.com/gke-tpu-topology`; a workload claims chips by
requesting the `google.com/tpu` extended resource with matching
nodeSelectors.  This cloud maps the framework's accelerator strings
(`tpu-v5e-8`, ...) onto those selectors; the provision impl
(provision/kubernetes) creates one pod per TPU host plus a headless
service for stable pod DNS.

Opt-in like the `local` cloud: never chosen by the optimizer unless the
task pins `cloud: kubernetes` (most users have no kubeconfig).
Cluster-internal capacity is priced at $0 (parity: the reference treats
self-hosted k8s as free and lets the optimizer prefer it).
"""
import shutil
import subprocess
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.clouds.cloud import Cloud, CloudCapability

# Framework TPU generation -> GKE accelerator label value.  v4/v5p are
# deliberately absent: their GKE topology labels are 3D (e.g. 2x2x4)
# while the catalog records the 2D host grid — mapping them needs a
# separate table, and v5e/v6e are the mainstream GKE TPU targets.
_GKE_ACCELERATOR = {
    'v5e': 'tpu-v5-lite-podslice',
    'v6e': 'tpu-v6e-slice',
}

def gke_selectors(accelerator: Optional[str]) -> Dict[str, str]:
    """accelerator string -> GKE nodeSelector labels (empty for CPU).
    The slice topology comes from the catalog (the same physical shape
    the TPU-VM path uses); only the accelerator label needs mapping."""
    if not accelerator:
        return {}
    from skypilot_tpu import catalog
    info = catalog.get_slice_info(accelerator)   # raises on unknown
    gke_acc = _GKE_ACCELERATOR.get(info.generation)
    if gke_acc is None:
        raise exceptions.InvalidResourcesError(
            f'no GKE podslice mapping for {accelerator!r} (generation '
            f'{info.generation}); kubernetes currently supports '
            f'{sorted(_GKE_ACCELERATOR)} — use cloud: gcp for the rest')
    return {
        'cloud.google.com/gke-tpu-accelerator': gke_acc,
        'cloud.google.com/gke-tpu-topology': info.topology,
    }


class Kubernetes(Cloud):
    NAME = 'kubernetes'

    def capabilities(self) -> set:
        # No STOP: pods terminate, they don't stop.  No AUTOSTOP:
        # autodown runs ON the head host, and pods carry no kubectl/
        # RBAC to delete themselves — advertising it would leak idle
        # TPU pods.  SPOT maps to GKE spot node pools (the scheduler
        # lands on them via the `cloud.google.com/gke-spot` selector).
        return {
            CloudCapability.SPOT,
            CloudCapability.MULTI_HOST,
            CloudCapability.HOST_CONTROLLERS,
            CloudCapability.OPEN_PORTS,
        }

    def get_feasible_resources(self, resources) -> List[Any]:
        if resources.cloud not in ('kubernetes', 'k8s'):
            return []   # opt-in
        if resources.accelerator:
            gke_selectors(resources.accelerator)   # validate mapping
            if resources.num_hosts > 1:
                # Fail BEFORE provisioning: the gang driver cannot yet
                # fan out across pods (no sshd in images; JobSet-style
                # launch is future work) — rejecting here beats paying
                # 30 min of podslice scheduling first.
                raise exceptions.InvalidResourcesError(
                    f'{resources.accelerator} spans '
                    f'{resources.num_hosts} hosts; multi-host podslices '
                    'are not yet supported on kubernetes — use '
                    'cloud: gcp for multi-host slices')
        return [resources]

    def region_zones_for(self, resources) -> Iterator[Tuple[str,
                                                            Optional[str]]]:
        # One "region" per kube-context; the active context is the
        # deploy target (parity: the reference's allowed_contexts).
        yield self.current_context() or 'in-cluster', None

    def hourly_cost(self, resources) -> float:
        return 0.0   # self-hosted cluster capacity

    def make_deploy_variables(self, resources, cluster_name: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        num_hosts = resources.num_hosts if resources.is_tpu else 1
        return {
            'cluster_name': cluster_name,
            'node_kind': 'kubernetes',
            'context': region,
            'num_hosts': num_hosts,
            'num_slices': getattr(resources, 'num_slices', 1),
            'chips_per_host': resources.chips_per_host,
            'accelerator': resources.accelerator,
            'node_selectors': gke_selectors(resources.accelerator),
            'use_spot': resources.use_spot,
        }

    @staticmethod
    def current_context() -> Optional[str]:
        if not shutil.which('kubectl'):
            return None
        try:
            res = subprocess.run(
                ['kubectl', 'config', 'current-context'],
                capture_output=True, text=True, timeout=10)
        except (subprocess.TimeoutExpired, OSError):
            return None
        return res.stdout.strip() if res.returncode == 0 else None

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if not shutil.which('kubectl'):
            return False, 'kubectl not found on PATH'
        ctx = self.current_context()
        if not ctx:
            return False, 'no current kube-context (kubectl config ...)'
        return True, None

    def get_active_user_identity(self) -> Optional[List[str]]:
        ctx = self.current_context()
        return [ctx] if ctx else None
