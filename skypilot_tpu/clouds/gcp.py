"""GCP cloud: TPU pod slices (tpu.googleapis.com) + controller CPU VMs.

Parity: sky/clouds/gcp.py — but TPU-first instead of TPU-aware: the
reference bolts TPUs onto a GPU/VM model ('TPU-VM' pseudo instance type,
sky/clouds/gcp.py:238); here the slice IS the unit, and plain VMs exist only
to host the jobs/serve controllers.
"""
import os
import subprocess
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import config as config_lib
from skypilot_tpu.clouds.cloud import Cloud, CloudCapability

DEFAULT_CONTROLLER_VM = 'n2-standard-8'


class GCP(Cloud):
    NAME = 'gcp'

    def capabilities(self) -> set:
        return {
            CloudCapability.SPOT,
            CloudCapability.OPEN_PORTS,
            CloudCapability.MULTI_HOST,
            CloudCapability.MULTI_SLICE,
            CloudCapability.STORAGE_MOUNT,
            CloudCapability.HOST_CONTROLLERS,
            # STOP/AUTOSTOP supported for CPU VMs only; TPU slices must be
            # deleted (autostop => autodown for slices). Checked per-resource
            # in unsupported_capabilities_for().
            CloudCapability.STOP,
            CloudCapability.AUTOSTOP,
        }

    def unsupported_capabilities_for(self, resources) -> Dict[
            CloudCapability, str]:
        out = {}
        if resources.is_tpu:
            # TPU slices cannot be stopped and restarted in place: the slice's
            # ICI fabric allocation is released on stop. (The reference blocks
            # stop on TPU pods similarly, sky/clouds/gcp.py:190-200.)
            out[CloudCapability.STOP] = (
                'TPU slices cannot be stopped; use autostop with down=True '
                '(autodown) instead.')
        return out

    # -------------------------------------------------------- feasibility

    def get_feasible_resources(self, resources) -> List[Any]:
        if resources.cloud not in (None, 'gcp'):
            return []
        r = resources.copy(cloud='gcp')
        if r.is_tpu:
            if not catalog.accelerator_exists(r.accelerator):
                return []
            try:
                catalog.validate_region_zone(r.accelerator, r.region, r.zone)
            except Exception:  # pylint: disable=broad-except
                return []
            return [r]
        # CPU-only: resolve cpus/memory to a concrete instance type.
        if r.instance_type is None:
            instance = catalog.get_vm_for_cpus(r.cpus, r.memory)
            if instance is None:
                return []
            r = r.copy(instance_type=instance)
        return [r]

    def region_zones_for(self, resources) -> Iterator[Tuple[str,
                                                            Optional[str]]]:
        if resources.is_tpu:
            pairs = catalog.get_regions_zones(resources.accelerator)
        else:
            instance = resources.instance_type or catalog.get_vm_for_cpus(
                resources.cpus, resources.memory)
            pairs = catalog.get_vm_regions_zones(instance)
        for region, zone in pairs:
            if resources.region is not None and region != resources.region:
                continue
            if resources.zone is not None and zone != resources.zone:
                continue
            yield region, zone

    # ------------------------------------------------------------ pricing

    def hourly_cost(self, resources) -> float:
        return resources.get_cost(3600)

    def egress_cost_per_gb(self, num_gb: float) -> float:
        # Simplified public tiered egress pricing.
        if num_gb <= 0:
            return 0.0
        if num_gb <= 1024:
            return 0.12
        if num_gb <= 10240:
            return 0.11
        return 0.08

    # ---------------------------------------------------------- deployment

    def make_deploy_variables(self, resources, cluster_name: str,
                              region: str, zone: Optional[str]) -> Dict[str,
                                                                        Any]:
        project = self.get_project_id()
        base = {
            'cluster_name': cluster_name,
            'project_id': project,
            'region': region,
            'zone': zone,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'labels': resources.labels or {},
            'ports': resources.ports or [],
        }
        if resources.is_tpu:
            info = resources.slice_info
            base.update({
                'node_kind': 'tpu_slice',
                'accelerator': info.accelerator,
                'tpu_type': _gcp_accelerator_type(info),
                'topology': info.topology,
                'runtime_version': resources.runtime_version,
                'num_hosts': info.hosts,
                'chips_per_host': info.chips_per_host,
                'reservation': resources.reservation,
                'network': resources.accelerator_args.get('network'),
                'subnetwork': resources.accelerator_args.get('subnetwork'),
                'queued_resource':
                    bool(resources.accelerator_args.get('queued_resource')),
            })
        else:
            instance = resources.instance_type or catalog.get_vm_for_cpus(
                resources.cpus, resources.memory)
            base.update({
                'node_kind': 'vm',
                'instance_type': instance,
                'image_id': resources.image_id,
                'num_hosts': 1,
            })
        return base

    # --------------------------------------------------------- credentials

    def get_project_id(self) -> Optional[str]:
        project = config_lib.get_nested(('gcp', 'project_id'))
        if project:
            return project
        project = os.environ.get('GOOGLE_CLOUD_PROJECT')
        if project:
            return project
        try:
            out = subprocess.run(
                ['gcloud', 'config', 'get-value', 'project'],
                capture_output=True, text=True, timeout=10, check=False)
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip()
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return None

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        adc = os.environ.get('GOOGLE_APPLICATION_CREDENTIALS')
        if adc and os.path.exists(os.path.expanduser(adc)):
            if self.get_project_id() is None:
                return False, ('Found credentials but no project id; set '
                               'gcp.project_id in ~/.skytpu/config.yaml or '
                               'GOOGLE_CLOUD_PROJECT.')
            return True, None
        default_adc = os.path.expanduser(
            '~/.config/gcloud/application_default_credentials.json')
        if os.path.exists(default_adc):
            if self.get_project_id() is None:
                return False, ('Found application-default credentials but no '
                               'project id configured.')
            return True, None
        return False, (
            'GCP credentials not found. Run `gcloud auth '
            'application-default login`, or set '
            'GOOGLE_APPLICATION_CREDENTIALS.')

    def get_active_user_identity(self) -> Optional[List[str]]:
        # [account, project] — changes when the user switches accounts.
        try:
            out = subprocess.run(
                ['gcloud', 'config', 'get-value', 'account'],
                capture_output=True, text=True, timeout=10, check=False)
            account = out.stdout.strip() if out.returncode == 0 else None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            account = None
        if not account:
            return None
        return [account, self.get_project_id() or '']


def _gcp_accelerator_type(info: catalog.SliceInfo) -> str:
    """Catalog name -> GCP acceleratorType string ('v5litepod-16')."""
    size = info.chips if info.generation in ('v5e', 'v6e') else info.chips * 2
    gen = 'v5litepod' if info.generation == 'v5e' else info.generation
    return f'{gen}-{size}'
