"""Abstract cloud interface.

Parity: sky/clouds/cloud.py:116 — feasibility, pricing hooks, deploy
variables, credential checks, capability flags — reduced to what a TPU-first
framework needs (two concrete clouds: GCP and Local).
"""
import enum
from typing import Any, Dict, Iterator, List, Optional, Tuple


class CloudCapability(enum.Enum):
    """Parity: CloudImplementationFeatures (sky/clouds/cloud.py:28)."""
    STOP = 'stop'
    AUTOSTOP = 'autostop'
    SPOT = 'spot'
    OPEN_PORTS = 'open_ports'
    MULTI_HOST = 'multi_host'
    MULTI_SLICE = 'multi_slice'   # gang width > 1 (task.num_nodes)
    STORAGE_MOUNT = 'storage_mount'
    HOST_CONTROLLERS = 'host_controllers'


class Region:
    def __init__(self, name: str, zones: Optional[List[str]] = None):
        self.name = name
        self.zones = zones or []

    def __repr__(self):
        return f'Region({self.name}, zones={self.zones})'


class Cloud:
    """A provider of slices/VMs.  Subclasses are stateless singletons."""

    NAME = 'abstract'
    _REGISTRY: Dict[str, 'Cloud'] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if cls.NAME != 'abstract':
            Cloud._REGISTRY[cls.NAME] = cls()

    # ----------------------------------------------------------- registry

    @classmethod
    def from_name(cls, name: Optional[str]) -> Optional['Cloud']:
        if name is None:
            return None
        # Import concrete clouds on first use (registers subclasses).
        from skypilot_tpu.clouds import gcp, local  # noqa: F401  pylint: disable=unused-import
        cloud = cls._REGISTRY.get(name.lower())
        if cloud is None:
            from skypilot_tpu import exceptions
            raise exceptions.InvalidResourcesError(
                f'Unknown cloud {name!r}. Supported: '
                f'{sorted(cls._REGISTRY)}')
        return cloud

    @classmethod
    def all_clouds(cls) -> List['Cloud']:
        from skypilot_tpu.clouds import gcp, local  # noqa: F401  pylint: disable=unused-import
        return list(cls._REGISTRY.values())

    # ------------------------------------------------------- capabilities

    def capabilities(self) -> set:
        raise NotImplementedError

    def supports(self, cap: CloudCapability) -> bool:
        return cap in self.capabilities()

    def unsupported_capabilities_for(self, resources) -> Dict[
            CloudCapability, str]:
        """Map of capability -> reason, for caps this placement lacks."""
        return {}

    # -------------------------------------------------------- feasibility

    def get_feasible_resources(self, resources) -> List[Any]:
        """Concrete launchable Resources (zone-unpinned) matching the
        request, or [] if infeasible.  Parity:
        sky/clouds/cloud.py:369 get_feasible_launchable_resources."""
        raise NotImplementedError

    def region_zones_for(self, resources) -> Iterator[Tuple[str,
                                                            Optional[str]]]:
        """Yield (region, zone) candidates in provisioning order.

        TPU spot capacity is zone-granular, so TPUs yield per-zone (parity:
        _yield_zones, sky/backends/cloud_vm_ray_backend.py:1178).
        """
        raise NotImplementedError

    # ------------------------------------------------------------ pricing

    def hourly_cost(self, resources) -> float:
        raise NotImplementedError

    def egress_cost_per_gb(self, num_gb: float) -> float:
        return 0.0

    # ---------------------------------------------------------- deployment

    def make_deploy_variables(self, resources, cluster_name: str,
                              region: str, zone: Optional[str]) -> Dict[str,
                                                                        Any]:
        """Variables consumed by the provisioner for this placement.
        Parity: make_deploy_resources_variables (sky/clouds/gcp.py:456)."""
        raise NotImplementedError

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not)."""
        raise NotImplementedError

    def get_active_user_identity(self) -> Optional[List[str]]:
        return None

    def __repr__(self):
        return self.NAME.upper() if self.NAME == 'gcp' else self.NAME.title()
