"""Local cloud: simulates slices with localhost processes & directories.

This is the framework's dev/test backend — the analog of the reference's
LocalDockerBackend (sky/backends/local_docker_backend.py) *and* its
fake-cloud test tier (tests/common.py enable_all_clouds_in_monkeypatch):
a "host" is a directory under $SKYTPU_HOME/local_cloud/<cluster>/<host_i>,
commands run via subprocess, and multi-host fan-out exercises the exact same
backend/podlet code paths as real TPU slices.  Provisioning latency and
stockouts are injectable for failover tests.
"""
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.clouds.cloud import Cloud, CloudCapability

# Tests can set this to simulate stockouts: {zone: Exception-to-raise}.
FAULT_INJECTION: Dict[str, Any] = {}

_ZONES = ['local-a', 'local-b', 'local-c']


class Local(Cloud):
    NAME = 'local'

    def capabilities(self) -> set:
        return {
            CloudCapability.SPOT,
            CloudCapability.MULTI_HOST,
            CloudCapability.MULTI_SLICE,
            CloudCapability.AUTOSTOP,
            CloudCapability.STOP,
            CloudCapability.HOST_CONTROLLERS,
            CloudCapability.OPEN_PORTS,
        }

    def get_feasible_resources(self, resources) -> List[Any]:
        if resources.cloud != 'local':
            # Local is opt-in: never chosen unless explicitly requested.
            return []
        return [resources]

    def region_zones_for(self, resources) -> Iterator[Tuple[str,
                                                            Optional[str]]]:
        for zone in _ZONES:
            if resources.zone is not None and zone != resources.zone:
                continue
            yield 'local', zone

    def hourly_cost(self, resources) -> float:
        return 0.0

    def make_deploy_variables(self, resources, cluster_name: str,
                              region: str, zone: Optional[str]) -> Dict[str,
                                                                        Any]:
        num_hosts = resources.num_hosts if resources.is_tpu else 1
        return {
            'cluster_name': cluster_name,
            'node_kind': 'local',
            'region': region,
            'zone': zone,
            'num_hosts': num_hosts,
            'chips_per_host': resources.chips_per_host,
            'use_spot': resources.use_spot,
            'accelerator': resources.accelerator,
        }

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        return True, None

    def get_active_user_identity(self) -> Optional[List[str]]:
        from skypilot_tpu.utils import common
        return [common.get_user_hash()]
