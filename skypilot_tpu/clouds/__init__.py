"""Cloud abstraction layer (parity: sky/clouds/)."""
from skypilot_tpu.clouds.cloud import Cloud, CloudCapability, Region
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.local import Local

__all__ = ['Cloud', 'CloudCapability', 'Region', 'GCP', 'Kubernetes',
           'Local']
