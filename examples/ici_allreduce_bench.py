"""ICI all-reduce bandwidth benchmark (analog of the reference's
examples/nccl_test.yaml, which times NCCL all-reduce over VPC TCP).

On TPU the all-reduce rides the ICI torus and is emitted by XLA from a
`jax.lax.psum` inside `shard_map` — there is no NCCL and nothing to
install.  Reports algorithm bandwidth (payload/time) and bus bandwidth
(algbw * 2*(n-1)/n, the ring-transfer bound), matching the metrics the
NCCL benchmark prints so numbers are directly comparable.

Reference anchor: 2x A100:8 over VPC reaches busbw 3.85 GBps
(examples/nccl_test.yaml:8-16).  A single v5e slice's ICI is two orders
of magnitude faster; this script is how you show that.

Runs on any JAX platform: multi-host TPU (via podlet env), single host,
or a CPU mesh for testing (JAX_PLATFORMS=cpu XLA_FLAGS=...device_count=8).
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:
    from jax.experimental.shard_map import shard_map


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--payload-mb', type=float, default=1024.0,
                        help='All-reduce payload per device, MB.')
    parser.add_argument('--trials', type=int, default=5)
    parser.add_argument('--dtype', default='bfloat16',
                        choices=['bfloat16', 'float32'])
    args = parser.parse_args()

    try:
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh_lib.initialize_distributed_from_env()
    except ImportError:
        pass  # standalone run without the framework installed

    n = len(jax.devices())
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ('x',))
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    itemsize = 2 if args.dtype == 'bfloat16' else 4
    per_dev_elems = int(args.payload_mb * 1e6 / itemsize)
    payload_bytes = per_dev_elems * itemsize

    @jax.jit
    def allreduce(x):
        return shard_map(lambda s: jax.lax.psum(s, 'x'), mesh=mesh,
                         in_specs=P('x'), out_specs=P('x'))(x)

    sharding = NamedSharding(mesh, P('x'))
    x = jax.device_put(
        jnp.ones((n * per_dev_elems,), dtype=dtype), sharding)

    # Warmup: compile the collective AND the per-trial sync expression so
    # neither lands inside a timed trial.
    float(jnp.sum(allreduce(x)[:1]))

    times = []
    for _ in range(args.trials):
        t0 = time.time()
        y = allreduce(x)
        # Host transfer = reliable sync on tunneled TPU platforms.
        float(jnp.sum(y[:1]))
        times.append(time.time() - t0)

    avg = sum(times) / len(times)
    algbw = payload_bytes / avg / 1e9
    busbw = algbw * 2 * (n - 1) / n
    print(f'The average bandwidth of all_reduce with a '
          f'{payload_bytes / 1e9:.1f}GB payload per device '
          f'({args.trials} trials, {n} devices, {args.dtype}):')
    print(f' algbw: {algbw:.3f} GBps ({algbw * 8:.1f} Gbps)')
    print(f' busbw: {busbw:.3f} GBps ({busbw * 8:.1f} Gbps)')


if __name__ == '__main__':
    main()
