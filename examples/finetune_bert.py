"""BERT sequence-classification finetune (IMDB-style) on TPU.

Analog of the reference's examples/huggingface_glue_imdb_app.yaml
(HF run_glue.py on a provisioned GPU VM), rebuilt JAX-native on the
framework's BERT family: data-parallel over the device mesh, bf16
encoder on the MXU, one jit'd train step.

Data: `--dataset imdb` tokenizes the real IMDB set via `datasets` +
`transformers` when those are installed; `--dataset synthetic` (the
hermetic default for CI) generates a *learnable* stand-in — each
sequence is drawn from a class-conditioned token distribution, so
accuracy above chance proves the end-to-end learning path, not just
that the step runs.

Examples:
  # v5e-8, real IMDB:
  python examples/finetune_bert.py --model bert-base --dataset imdb

  # hermetic CPU smoke:
  python examples/finetune_bert.py --model bert-debug \
      --dataset synthetic --steps 30 --batch-size 8 --seq-len 64
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def synthetic_batches(rng: np.random.Generator, vocab_size: int,
                      batch_size: int, seq_len: int, num_classes: int):
    """Class-conditioned token streams: class c favors the c-th slice of
    the vocabulary 3:1, so a linear probe over a [CLS] encoding can
    separate the classes — loss must fall and accuracy must rise."""
    bucket = max(vocab_size // num_classes, 1)
    while True:
        labels = rng.integers(0, num_classes, size=batch_size)
        favored = rng.integers(0, bucket, size=(batch_size, seq_len)) + \
            (labels[:, None] * bucket)
        uniform = rng.integers(0, vocab_size, size=(batch_size, seq_len))
        pick = rng.random((batch_size, seq_len)) < 0.75
        tokens = np.where(pick, favored, uniform)
        yield {'tokens': tokens.astype(np.int32),
               'labels': labels.astype(np.int32)}


def imdb_batches(batch_size: int, seq_len: int):
    """Real IMDB via `datasets` (needs network/installed data)."""
    try:
        import datasets  # type: ignore
        import transformers
    except ImportError as e:
        raise SystemExit(
            f'--dataset imdb needs the `datasets` package ({e}); '
            'use --dataset synthetic for a hermetic run') from e
    ds = datasets.load_dataset('imdb', split='train').shuffle(seed=0)
    tok = transformers.AutoTokenizer.from_pretrained('bert-base-uncased')
    while True:
        for i in range(0, len(ds) - batch_size, batch_size):
            rows = ds[i:i + batch_size]
            enc = tok(rows['text'], truncation=True, padding='max_length',
                      max_length=seq_len, return_tensors='np')
            yield {'tokens': enc['input_ids'].astype(np.int32),
                   'labels': np.asarray(rows['label'], np.int32)}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='bert-base')
    parser.add_argument('--dataset', default='synthetic',
                        choices=['synthetic', 'imdb'])
    parser.add_argument('--steps', type=int, default=200)
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--seq-len', type=int, default=128)
    parser.add_argument('--lr', type=float, default=2e-5)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--platform', default=None,
                        choices=['cpu', 'tpu'],
                        help='pin jax onto this platform (hosts whose '
                             'site hooks rewrite JAX_PLATFORMS need the '
                             'post-import pin; hermetic CI uses cpu)')
    args = parser.parse_args()
    if args.platform:
        jax.config.update('jax_platforms', args.platform)

    from skypilot_tpu.models import get_model_config
    from skypilot_tpu.models.bert import BertForSequenceClassification
    from skypilot_tpu.parallel import MeshSpec, make_mesh, mesh as mesh_lib

    mesh_lib.initialize_distributed_from_env()
    mesh = make_mesh(MeshSpec(data=len(jax.devices())))
    P = jax.sharding.PartitionSpec

    def put(tree, pspec):
        """Host values -> global arrays on the mesh.  Multi-process:
        each process contributes its LOCAL rows (host_local -> global);
        single-process: plain device_put."""
        if jax.process_count() == 1:
            return jax.device_put(
                tree, jax.sharding.NamedSharding(mesh, pspec))
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            tree, mesh, pspec)

    cfg = get_model_config(args.model)
    model = BertForSequenceClassification(cfg)
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, args.seq_len), jnp.int32))
    opt = optax.adamw(args.lr, weight_decay=0.01)
    opt_state = put(opt.init(params), P())
    params = put(params, P())      # same seed everywhere -> replicated

    def loss_fn(p, tokens, labels):
        logits = model.apply(p, tokens)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, acc

    @jax.jit
    def step(p, s, batch):
        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, batch['tokens'], batch['labels'])
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss, acc

    nproc = jax.process_count()
    if args.batch_size % nproc:
        raise SystemExit(f'--batch-size {args.batch_size} must divide '
                         f'across {nproc} processes')
    local_bs = args.batch_size // nproc
    if local_bs % jax.local_device_count():
        raise SystemExit(
            f'per-process batch {local_bs} must divide by the '
            f'{jax.local_device_count()} local devices')
    rng = np.random.default_rng(args.seed * 1000 + jax.process_index())
    batches = (synthetic_batches(rng, cfg.vocab_size, local_bs,
                                 args.seq_len, cfg.num_classes)
               if args.dataset == 'synthetic' else
               imdb_batches(local_bs, args.seq_len))
    t0 = time.time()
    first_loss = last_acc = None
    for i in range(args.steps):
        batch = put(next(batches), P('data'))
        params, opt_state, loss, acc = step(params, opt_state, batch)
        if i % 10 == 0 or i == args.steps - 1:
            loss, acc = float(loss), float(acc)
            if first_loss is None:
                first_loss = loss
            last_acc = acc
            print(f'step {i}: loss {loss:.4f} acc {acc:.3f}', flush=True)
    elapsed = time.time() - t0
    seqs = args.steps * args.batch_size
    print(f'done: {seqs / elapsed:.1f} sequences/s, final acc '
          f'{last_acc:.3f} (first loss {first_loss:.4f})')


if __name__ == '__main__':
    main()
