"""Distributed ResNet training on TPU (data-parallel over the mesh).

Analog of the reference's examples/resnet_distributed_torch.yaml
(torch DDP over N GPU nodes via torch.distributed.launch), rebuilt
JAX-native: one jit'd SGD step with the batch sharded over the mesh's
data axis — XLA inserts the gradient all-reduce over ICI, no
torchrun/master_addr plumbing (multi-host rendezvous comes from the
framework env via initialize_distributed_from_env).

Data: CIFAR-shaped synthetic images by default (hermetic, no egress):
each class gets a fixed random mean image + noise, so the model must
actually learn class structure — accuracy above chance proves the
training path end to end.  `--data-dir` points at a CIFAR-10 python
pickle tree for the real thing.

Examples:
  # v5e-8 single host:
  python examples/train_resnet.py --model resnet50 --batch-size 256

  # hermetic CPU smoke:
  python examples/train_resnet.py --model resnet18-debug \
      --steps 30 --batch-size 16 --image-size 32 --num-classes 4
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def synthetic_batches(seed: int, proc_seed: int, batch_size: int,
                      image_size: int, num_classes: int):
    """Class-conditioned images: fixed per-class mean + gaussian noise.
    The class means come from `seed` alone so every process of a
    distributed run learns the SAME task; the sample stream is offset
    by `proc_seed` so shards differ."""
    means = np.random.default_rng(seed).normal(
        0.0, 1.0, size=(num_classes, image_size, image_size, 3))
    rng = np.random.default_rng(seed * 1000 + proc_seed + 1)
    while True:
        labels = rng.integers(0, num_classes, size=batch_size)
        images = means[labels] + rng.normal(
            0.0, 0.8, size=(batch_size, image_size, image_size, 3))
        yield {'images': images.astype(np.float32),
               'labels': labels.astype(np.int32)}


def cifar_batches(data_dir: str, batch_size: int, proc_seed: int = 0):
    """CIFAR-10 python-pickle batches (the reference recipe's dataset).
    `proc_seed` de-correlates the shards of a distributed run."""
    import glob
    import pickle
    files = sorted(glob.glob(f'{data_dir}/data_batch_*'))
    if not files:
        raise SystemExit(f'no CIFAR data_batch_* under {data_dir}')
    xs, ys = [], []
    for f in files:
        with open(f, 'rb') as fh:
            d = pickle.load(fh, encoding='bytes')
        xs.append(np.asarray(d[b'data'], np.float32).reshape(
            -1, 3, 32, 32).transpose(0, 2, 3, 1) / 127.5 - 1.0)
        ys.append(np.asarray(d[b'labels'], np.int32))
    x, y = np.concatenate(xs), np.concatenate(ys)
    rng = np.random.default_rng(proc_seed)
    while True:
        order = rng.permutation(len(x))
        for i in range(0, len(order) - batch_size, batch_size):
            idx = order[i:i + batch_size]
            yield {'images': x[idx], 'labels': y[idx]}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='resnet50')
    parser.add_argument('--steps', type=int, default=200)
    parser.add_argument('--batch-size', type=int, default=256,
                        help='global batch (sharded over the data axis)')
    parser.add_argument('--image-size', type=int, default=32)
    parser.add_argument('--num-classes', type=int, default=10)
    parser.add_argument('--lr', type=float, default=0.1)
    parser.add_argument('--momentum', type=float, default=0.9)
    parser.add_argument('--data-dir', default=None,
                        help='CIFAR-10 pickle dir; default synthetic')
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--platform', default=None,
                        choices=['cpu', 'tpu'],
                        help='pin jax onto this platform (hosts whose '
                             'site hooks rewrite JAX_PLATFORMS need the '
                             'post-import pin; hermetic CI uses cpu)')
    args = parser.parse_args()
    if args.platform:
        jax.config.update('jax_platforms', args.platform)

    import dataclasses

    from skypilot_tpu.models import get_model_config
    from skypilot_tpu.models.resnet import ResNet
    from skypilot_tpu.parallel import MeshSpec, make_mesh, mesh as mesh_lib

    mesh_lib.initialize_distributed_from_env()
    mesh = make_mesh(MeshSpec(data=len(jax.devices())))
    P = jax.sharding.PartitionSpec

    def put(tree, pspec):
        """Host values -> global arrays on the mesh.  Multi-process:
        each process contributes its LOCAL rows (host_local -> global);
        single-process: plain device_put."""
        if jax.process_count() == 1:
            return jax.device_put(
                tree, jax.sharding.NamedSharding(mesh, pspec))
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            tree, mesh, pspec)

    cfg = dataclasses.replace(get_model_config(args.model),
                              num_classes=args.num_classes)
    model = ResNet(cfg)
    variables = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, args.image_size, args.image_size, 3)), train=True)
    opt = optax.sgd(optax.cosine_decay_schedule(args.lr, args.steps),
                    momentum=args.momentum, nesterov=True)
    opt_state = put(opt.init(variables['params']), P())
    state = put({'params': variables['params'],
                 'batch_stats': variables['batch_stats']},
                P())               # same seed everywhere -> replicated

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {'params': params, 'batch_stats': batch_stats}, images,
            train=True, mutable=['batch_stats'])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, (acc, mutated['batch_stats'])

    @jax.jit
    def step(state, opt_state, batch):
        (loss, (acc, stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state['params'], state['batch_stats'],
                                   batch['images'], batch['labels'])
        updates, opt_state = opt.update(grads, opt_state, state['params'])
        params = optax.apply_updates(state['params'], updates)
        return ({'params': params, 'batch_stats': stats}, opt_state,
                loss, acc)

    nproc = jax.process_count()
    if args.batch_size % nproc:
        raise SystemExit(f'--batch-size {args.batch_size} must divide '
                         f'across {nproc} processes')
    local_bs = args.batch_size // nproc
    if local_bs % jax.local_device_count():
        raise SystemExit(
            f'per-process batch {local_bs} must divide by the '
            f'{jax.local_device_count()} local devices')
    batches = (cifar_batches(args.data_dir, local_bs,
                             jax.process_index())
               if args.data_dir else
               synthetic_batches(args.seed, jax.process_index(),
                                 local_bs, args.image_size,
                                 args.num_classes))
    t0 = time.time()
    last_acc = None
    for i in range(args.steps):
        batch = put(next(batches), P('data'))
        state, opt_state, loss, acc = step(state, opt_state, batch)
        if i % 10 == 0 or i == args.steps - 1:
            last_acc = float(acc)
            print(f'step {i}: loss {float(loss):.4f} acc {last_acc:.3f}',
                  flush=True)
    elapsed = time.time() - t0
    print(f'done: {args.steps * args.batch_size / elapsed:.1f} images/s, '
          f'final acc {last_acc:.3f}')


if __name__ == '__main__':
    main()
