"""Llama training recipe on a TPU slice using the built-in trainer.

Analog of the reference's torch-XLA FSDP recipe
(examples/tpu/v6e/train-llama3-8b.yaml, docs/source/reference/tpu.rst:
--fsdp "full_shard" --block_size 8192), rebuilt JAX-native: the model is
FSDP-sharded over the mesh by the trainer's NamedSharding annotations and
the step is one pjit'd function; multi-host rendezvous comes from the env
the framework exports on every host (no torchrun/hostfile).

Checkpoint/resume contract: pass --checkpoint-dir at a MOUNTed bucket
path; managed-job recovery restores the latest step on a fresh slice
(checkpoints are keyed by step, the task keeps its stable SKYTPU_TASK_ID
across recoveries).

Examples:
  # v5e-8 single host, 1B model:
  python examples/train_llama.py --model llama-1b --steps 200

  # v5e-64 multi-host FSDP, 8B model, long context:
  python examples/train_llama.py --model llama3-8b --seq-len 8192 \
      --batch-size 32 --fsdp 64
"""
import argparse

import jax


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-1b')
    parser.add_argument('--steps', type=int, default=200)
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--seq-len', type=int, default=2048)
    parser.add_argument('--lr', type=float, default=3e-4)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=100)
    # Mesh axes; defaults to FSDP over all devices.
    parser.add_argument('--data', type=int, default=1)
    parser.add_argument('--fsdp', type=int, default=0,
                        help='0 = all remaining devices')
    parser.add_argument('--tensor', type=int, default=1)
    parser.add_argument('--seq', type=int, default=1)
    args = parser.parse_args()

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import trainer as trainer_lib

    mesh_lib.initialize_distributed_from_env()
    n = len(jax.devices())
    fsdp = args.fsdp or n // (args.data * args.tensor * args.seq)
    spec = mesh_lib.MeshSpec(data=args.data, fsdp=fsdp,
                             tensor=args.tensor, seq=args.seq)
    cfg = trainer_lib.TrainConfig(
        model=args.model,
        batch_size=args.batch_size,
        seq_len=args.seq_len,
        learning_rate=args.lr,
        total_steps=args.steps,
        mesh=spec,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
    )
    trainer = trainer_lib.Trainer(cfg)
    trainer.setup()
    start = int(trainer.state.step)
    if start:
        print(f'resumed from checkpoint at step {start}')
    remaining = args.steps - start
    if remaining <= 0:
        # Recovery after the final checkpoint: nothing left to train.
        print(f'already at step {start} >= --steps {args.steps}; done')
        return
    metrics = trainer.train(num_steps=remaining)
    print(f"final loss {metrics['final_loss']:.4f}; "
          f"{metrics['tokens_per_second']:,.0f} tokens/s "
          f"({metrics['tokens_per_second_per_device']:,.0f} tok/s/chip)")


if __name__ == '__main__':
    main()
