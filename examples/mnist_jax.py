"""Minimal JAX MNIST-style training — the 'hello world' recipe.

Analog of the reference's examples/tpu/tpuvm_mnist.yaml (which clones the
flax repo and runs its MNIST example).  Self-contained instead: a small
convnet on synthetic 28x28 data (zero-egress environments can't download
MNIST; swap `synthetic_batches` for real data loading outside the demo).
Data-parallel over all local devices via a 1-axis mesh.
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ConvNet(nn.Module):
    hidden: int = 32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.hidden, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.hidden * 2, (3, 3))(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def synthetic_batches(batch_size: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    while True:
        x = rng.rand(batch_size, 28, 28, 1).astype(np.float32)
        y = rng.randint(0, 10, size=(batch_size,))
        yield x, y


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--steps', type=int, default=200)
    parser.add_argument('--batch-size', type=int, default=512)
    parser.add_argument('--hidden', type=int, default=32)
    parser.add_argument('--lr', type=float, default=1e-3)
    args = parser.parse_args()

    model = ConvNet(hidden=args.hidden)
    mesh = Mesh(np.array(jax.devices()), ('data',))
    data_sharding = NamedSharding(mesh, P('data'))
    replicated = NamedSharding(mesh, P())

    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))['params']
    params = jax.device_put(params, replicated)
    tx = optax.adam(args.lr)
    opt_state = jax.device_put(tx.init(params), replicated)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({'params': p}, x)
            one_hot = jax.nn.one_hot(y, 10)
            return optax.softmax_cross_entropy(logits, one_hot).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    data = synthetic_batches(args.batch_size)
    t0 = None
    for i in range(args.steps):
        x, y = next(data)
        x = jax.device_put(x, data_sharding)
        y = jax.device_put(y, data_sharding)
        params, opt_state, loss = step(params, opt_state, x, y)
        if i == 0:
            float(loss)  # sync: exclude compile from throughput
            t0 = time.time()
        if (i + 1) % 50 == 0 or i == args.steps - 1:
            print(f'step {i + 1}: loss {float(loss):.4f}')
    elapsed = time.time() - t0
    rate = args.batch_size * max(args.steps - 1, 1) / max(elapsed, 1e-9)
    print(f'throughput: {rate:,.0f} images/s on {len(jax.devices())} '
          f'device(s)')


if __name__ == '__main__':
    main()
