#!/usr/bin/env bash
# Tier-1 wrapper: the ROADMAP.md verify command plus --durations=15 and
# the duration-budget guard (scripts/check_tier1_budget.py).  The guard
# prints the slowest tests and fails the run when the suite eats into
# the 870 s tier-1 window's headroom — so a PR that adds slow tests is
# caught by name, before the window itself starts truncating the suite.
#
# Usage: bash scripts/run_tier1.sh [budget_seconds]
set -o pipefail
BUDGET="${1:-870}"
LOG=/tmp/_t1.log
SKYJSON=/tmp/_skycheck.json
rm -f "$LOG" "$SKYJSON"
rc=0
# Static analysis gate first: new findings (vs skycheck_baseline.txt)
# fail tier-1 before any pytest time is spent.  --json records each
# pass's own wall time; the budget guard charges them individually.
timeout -k 5 60 python scripts/skycheck.py \
    --baseline skycheck_baseline.txt --json "$SKYJSON" || rc=1
timeout -k 10 "$BUDGET" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly --durations=0 --durations-min=0.05 2>&1 | tee "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
# Decode-bench dryrun under the compile sanitizer: drives the REAL
# paged/dense jit roots across the nb ladder and asserts the measured
# compile counts stay inside the provable static bounds.
BENCH_T0=$(date +%s.%N)
timeout -k 10 240 env JAX_PLATFORMS=cpu SKYTPU_COMPILE_SANITIZER=1 SKYTPU_SHARD_SANITIZER=1 \
    python scripts/bench_decode_micro.py --paged --max-cache-len 256 \
    --fill-sweep 40 200 --out /tmp/_bench_paged.json || rc=1
BENCH_SECS=$(echo "$(date +%s.%N) $BENCH_T0" | awk '{print $1-$2}')
# --require: every tier-1 test file must actually reach the window —
# a file lost to a collection error or marker typo fails by name.
python scripts/check_tier1_budget.py "$LOG" --budget "$BUDGET" \
    --require tests/test_paged_kv.py --require tests/test_faults.py \
    --require tests/test_radix.py \
    --require tests/test_serve_failover.py \
    --require tests/test_skycheck.py \
    --require tests/test_lb_affinity.py \
    --require tests/test_qos.py \
    --require tests/test_tp_paged.py \
    --require tests/test_kv_tier.py \
    --require tests/test_control_plane.py \
    --require tests/test_batch_plane.py \
    --skycheck-json "$SKYJSON" \
    --extra-seconds "bench_dryrun:$BENCH_SECS" || rc=1
# Seeded chaos sweep (fault injection): no hang + full request
# accounting under randomized faults.  Outside the pytest window on
# purpose — it must not eat durations budget from the suite.  The
# compile sanitizer rides along: fault storms must not smuggle
# unbucketed shapes into the jit roots.
timeout -k 10 240 env JAX_PLATFORMS=cpu SKYTPU_COMPILE_SANITIZER=1 SKYTPU_SHARD_SANITIZER=1 \
    python scripts/chaos_smoke.py || rc=1
# Replica-plane chaos sweep (fixed seeds): seeded mid-decode replica
# kills behind the LB; every greedy request must complete
# byte-identical to the fault-free run, and a draining replica must
# finish its in-flight stream with zero 5xx at the LB.  Runs under
# prefix_affinity routing: byte-identity + failover must hold under
# the affinity policy too (least_load is covered by the pytest suite).
# One fleet replica is tensor-parallel (tp=2 dryrun) and the sweep
# runs under ALL FOUR sanitizers — lock order, block conservation,
# compile budget, and the shard-layout check that proves the
# head-sharded paged pool's committed leaves at drain.
timeout -k 10 420 env JAX_PLATFORMS=cpu SKYTPU_SANITIZERS=1 \
    python scripts/chaos_smoke.py --multi-replica 3 --seeds 0 1 \
    --requests 8 --policy prefix_affinity || rc=1
# Batch-plane chaos leg: one journaled batch job survives a replica
# kill, an LB kill/warm-restart (row-lease re-adoption), and a
# coordinator crash/resume mid-flight — final output byte-identical
# to the fault-free reference, zero lost or duplicated rows.
timeout -k 10 300 env JAX_PLATFORMS=cpu SKYTPU_SANITIZERS=1 \
    python scripts/chaos_smoke.py --batch || rc=1
exit "$rc"
