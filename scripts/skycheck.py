#!/usr/bin/env python3
"""skycheck: the repo's static-analysis suite (see skypilot_tpu/analysis).

Runs the lock-discipline, jit-boundary, layering, determinism,
wire-contract, block-lifecycle, compile-budget and sharding-contract
passes over the tree and compares findings against a checked-in
baseline:

    python scripts/skycheck.py --baseline skycheck_baseline.txt

Exit status is non-zero iff findings NOT pinned by the baseline exist
(comparison keys on (path, pass-id, message), so pure line shifts do
not churn).  Regenerate the baseline after deliberately accepting or
fixing findings:

    python scripts/skycheck.py --write-baseline skycheck_baseline.txt

The baseline is a RATCHET: rewriting it with MORE pinned findings than
it already holds is refused (exit 3) unless ``--allow-grow`` is given —
shrinking is always fine, growth is a decision someone must own.

``--passes lock,jit,...`` restricts which passes run (unknown names
are rejected with the available list); ``--all`` prints baselined
findings too.  ``--changed`` restricts the per-file passes to files
git reports as modified (fast pre-commit loop) — tree passes still
read the whole tree because their contracts span files, and tier-1
always runs the full sweep.  ``--json FILE`` (or ``--json -`` for
stdout) emits machine-readable results including PER-PASS wall time,
which run_tier1.sh feeds to check_tier1_budget.py so each pass is
charged for its own seconds.  Runs in well under the tier-1 budget
lines it is charged under.
"""
import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from skypilot_tpu.analysis import block_lifecycle  # noqa: E402
from skypilot_tpu.analysis import compile_budget  # noqa: E402
from skypilot_tpu.analysis import determinism  # noqa: E402
from skypilot_tpu.analysis import jit_boundary  # noqa: E402
from skypilot_tpu.analysis import layering  # noqa: E402
from skypilot_tpu.analysis import lock_discipline  # noqa: E402
from skypilot_tpu.analysis import shard_contract  # noqa: E402
from skypilot_tpu.analysis import wire_contract  # noqa: E402
from skypilot_tpu.analysis.findings import load_baseline  # noqa: E402
from skypilot_tpu.analysis.findings import new_findings  # noqa: E402
from skypilot_tpu.analysis.walker import iter_py_files  # noqa: E402

# Per-file passes: check_file(rel_path, text) -> [Finding].
PASSES = {
    'lock': lock_discipline.check_file,
    'jit': jit_boundary.check_file,
    'layer': layering.check_file,
    'det': determinism.check_file,
    'block': block_lifecycle.check_file,
    'compile': compile_budget.check_file,
}

# Whole-tree passes: check_tree({rel_path: text}) -> [Finding].  They
# see every file at once (the wire contract spans planes; the shard
# contract reads the mesh vocabulary out of parallel/mesh.py).
TREE_PASSES = {
    'wire': wire_contract.check_tree,
    'shard': shard_contract.check_tree,
}

ALL_PASSES = tuple(PASSES) + tuple(TREE_PASSES)

# Where hand-written, annotation-bearing sources live.
DEFAULT_SUBDIRS = ('skypilot_tpu', 'scripts', 'tests')


def changed_files(root):
    """Repo-relative paths git reports as modified (vs HEAD) or
    untracked — the --changed pre-commit scope.  Returns None (full
    sweep) when git is unavailable or this is not a work tree."""
    import subprocess
    out = set()
    for args in (['git', '-C', root, 'diff', '--name-only', 'HEAD'],
                 ['git', '-C', root, 'ls-files', '--others',
                  '--exclude-standard']):
        try:
            proc = subprocess.run(args, capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def run(root, subdirs, pass_names, only=None):
    """-> (findings, files_checked, {pass: seconds}).

    only: optional set of rel paths restricting the PER-FILE passes
    (--changed).  Tree passes always see the whole walked tree — their
    contracts span files, so a partial tree would under-report.
    """
    findings = []
    checked = 0
    timings = {name: 0.0 for name in pass_names}
    file_passes = [n for n in pass_names if n in PASSES]
    tree_passes = [n for n in pass_names if n in TREE_PASSES]
    files = {}
    for rel in iter_py_files(root, subdirs=subdirs):
        abs_path = os.path.join(root, rel.replace('/', os.sep))
        try:
            with open(abs_path, encoding='utf-8') as f:
                text = f.read()
        except OSError as e:
            print(f'skycheck: cannot read {rel}: {e}', file=sys.stderr)
            continue
        if tree_passes:
            files[rel] = text
        if only is not None and rel not in only:
            continue
        checked += 1
        for name in file_passes:
            t0 = time.monotonic()
            findings.extend(PASSES[name](rel, text))
            timings[name] += time.monotonic() - t0
    for name in tree_passes:
        t0 = time.monotonic()
        findings.extend(TREE_PASSES[name](files))
        timings[name] += time.monotonic() - t0
    return findings, checked, timings


def _write_baseline(path, findings, allow_grow):
    """Ratcheted rewrite: refuse growth unless explicitly allowed."""
    if os.path.exists(path) and not allow_grow:
        try:
            old = load_baseline(path)
        except ValueError as e:
            print(f'skycheck: existing baseline unreadable: {e}',
                  file=sys.stderr)
            return 2
        grown, _ = new_findings(findings, old)
        if grown:
            print(f'skycheck: refusing to GROW the baseline by '
                  f'{len(grown)} finding(s) (ratchet); fix them or '
                  're-run with --allow-grow to accept deliberately:',
                  file=sys.stderr)
            for fd in grown[:20]:
                print(f'  {fd.render()}', file=sys.stderr)
            if len(grown) > 20:
                print(f'  ... and {len(grown) - 20} more',
                      file=sys.stderr)
            return 3
    with open(path, 'w', encoding='utf-8') as f:
        f.write('# skycheck pinned findings -- regenerate with:\n'
                '#   python scripts/skycheck.py --write-baseline '
                f'{os.path.basename(path)}\n')
        for fd in findings:
            f.write(fd.render() + '\n')
    print(f'skycheck: wrote {len(findings)} finding(s) to {path}')
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--root', default=_REPO,
                    help='repo root to analyze (default: this repo)')
    ap.add_argument('--baseline', default=None,
                    help='pinned-findings file; new findings fail')
    ap.add_argument('--write-baseline', default=None, metavar='FILE',
                    help='write current findings as the new baseline '
                         '(refuses growth without --allow-grow)')
    ap.add_argument('--allow-grow', action='store_true',
                    help='let --write-baseline pin MORE findings than '
                         'the existing file (deliberate ratchet bump)')
    ap.add_argument('--passes', default=','.join(ALL_PASSES),
                    help=f'comma list of passes ({",".join(ALL_PASSES)})')
    ap.add_argument('--all', action='store_true',
                    help='print baselined findings too, not just new')
    ap.add_argument('--changed', action='store_true',
                    help='per-file passes only on git-modified files '
                         '(fast pre-commit loop; tree passes still '
                         'read the whole tree, and tier-1 always runs '
                         'the full sweep)')
    ap.add_argument('--json', default=None, metavar='FILE',
                    help='write machine-readable results (per-pass '
                         "seconds, counts, new findings); '-' = stdout")
    args = ap.parse_args(argv)

    pass_names = [p.strip() for p in args.passes.split(',') if p.strip()]
    unknown = [p for p in pass_names if p not in PASSES
               and p not in TREE_PASSES]
    if unknown:
        ap.error(f'unknown pass(es): {", ".join(unknown)} '
                 f'(available: {", ".join(ALL_PASSES)})')
    only = None
    if args.changed:
        only = changed_files(args.root)
        if only is None:
            print('skycheck: --changed needs a git work tree; running '
                  'the full sweep', file=sys.stderr)

    t0 = time.monotonic()
    findings, checked, timings = run(args.root, DEFAULT_SUBDIRS,
                                     pass_names, only=only)
    findings.sort()
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        return _write_baseline(args.write_baseline, findings,
                               args.allow_grow)

    baseline = {}
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except ValueError as e:
            print(f'skycheck: {e}', file=sys.stderr)
            return 2
    new, fixed = new_findings(findings, baseline)

    per_pass_findings = {name: 0 for name in pass_names}
    prefix_of = {name: name.upper() for name in pass_names}
    for fd in findings:
        for name in pass_names:
            if fd.pass_id.startswith(prefix_of[name]):
                per_pass_findings[name] += 1
                break

    payload = {
        'files_checked': checked,
        'elapsed_seconds': round(elapsed, 3),
        'passes': {name: {'seconds': round(timings[name], 3),
                          'findings': per_pass_findings[name]}
                   for name in pass_names},
        'total_findings': len(findings),
        'baselined': len(findings) - len(new),
        'new': [fd.render() for fd in new],
        'fixed': fixed,
    }
    if args.json == '-':
        print(json.dumps(payload, indent=2))
    elif args.json:
        with open(args.json, 'w', encoding='utf-8') as f:
            json.dump(payload, f, indent=2)
            f.write('\n')

    if args.json != '-':
        if args.all:
            for fd in findings:
                marker = 'NEW ' if fd in new else ''
                print(f'{marker}{fd.render()}')
        else:
            for fd in new:
                print(fd.render())
        pinned = len(findings) - len(new)
        print(f'skycheck: {checked} files, {len(findings)} finding(s) '
              f'({pinned} baselined, {len(new)} new, {fixed} fixed) '
              f'in {elapsed:.2f}s [{",".join(pass_names)}]')
        if fixed:
            print('skycheck: baseline has stale entries - shrink it '
                  'with --write-baseline')
    return 1 if new else 0


if __name__ == '__main__':
    sys.exit(main())
