#!/usr/bin/env python3
"""skycheck: the repo's static-analysis suite (see skypilot_tpu/analysis).

Runs the lock-discipline, jit-boundary, layering and determinism passes
over the tree and compares findings against a checked-in baseline:

    python scripts/skycheck.py --baseline skycheck_baseline.txt

Exit status is non-zero iff findings NOT pinned by the baseline exist
(comparison keys on (path, pass-id, message), so pure line shifts do
not churn).  Regenerate the baseline after deliberately accepting or
fixing findings:

    python scripts/skycheck.py --write-baseline skycheck_baseline.txt

``--passes lock,jit,layer,det`` restricts which passes run; ``--all``
prints baselined findings too.  Runs in well under the 30s tier-1
budget line it is charged under (see run_tier1.sh).
"""
import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from skypilot_tpu.analysis import determinism  # noqa: E402
from skypilot_tpu.analysis import jit_boundary  # noqa: E402
from skypilot_tpu.analysis import layering  # noqa: E402
from skypilot_tpu.analysis import lock_discipline  # noqa: E402
from skypilot_tpu.analysis.findings import load_baseline  # noqa: E402
from skypilot_tpu.analysis.findings import new_findings  # noqa: E402
from skypilot_tpu.analysis.walker import iter_py_files  # noqa: E402

PASSES = {
    'lock': lock_discipline.check_file,
    'jit': jit_boundary.check_file,
    'layer': layering.check_file,
    'det': determinism.check_file,
}

# Where hand-written, annotation-bearing sources live.
DEFAULT_SUBDIRS = ('skypilot_tpu', 'scripts', 'tests')


def run(root, subdirs, pass_names):
    findings = []
    checked = 0
    for rel in iter_py_files(root, subdirs=subdirs):
        abs_path = os.path.join(root, rel.replace('/', os.sep))
        try:
            with open(abs_path, encoding='utf-8') as f:
                text = f.read()
        except OSError as e:
            print(f'skycheck: cannot read {rel}: {e}', file=sys.stderr)
            continue
        checked += 1
        for name in pass_names:
            findings.extend(PASSES[name](rel, text))
    return findings, checked


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--root', default=_REPO,
                    help='repo root to analyze (default: this repo)')
    ap.add_argument('--baseline', default=None,
                    help='pinned-findings file; new findings fail')
    ap.add_argument('--write-baseline', default=None, metavar='FILE',
                    help='write current findings as the new baseline')
    ap.add_argument('--passes', default=','.join(PASSES),
                    help=f'comma list of passes ({",".join(PASSES)})')
    ap.add_argument('--all', action='store_true',
                    help='print baselined findings too, not just new')
    args = ap.parse_args(argv)

    pass_names = [p.strip() for p in args.passes.split(',') if p.strip()]
    unknown = [p for p in pass_names if p not in PASSES]
    if unknown:
        ap.error(f'unknown pass(es): {", ".join(unknown)}')

    t0 = time.monotonic()
    findings, checked = run(args.root, DEFAULT_SUBDIRS, pass_names)
    findings.sort()
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        with open(args.write_baseline, 'w', encoding='utf-8') as f:
            f.write('# skycheck pinned findings -- regenerate with:\n'
                    '#   python scripts/skycheck.py --write-baseline '
                    f'{os.path.basename(args.write_baseline)}\n')
            for fd in findings:
                f.write(fd.render() + '\n')
        print(f'skycheck: wrote {len(findings)} finding(s) to '
              f'{args.write_baseline}')
        return 0

    baseline = {}
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except ValueError as e:
            print(f'skycheck: {e}', file=sys.stderr)
            return 2
    new, fixed = new_findings(findings, baseline)

    if args.all:
        for fd in findings:
            marker = 'NEW ' if fd in new else ''
            print(f'{marker}{fd.render()}')
    else:
        for fd in new:
            print(fd.render())

    pinned = len(findings) - len(new)
    print(f'skycheck: {checked} files, {len(findings)} finding(s) '
          f'({pinned} baselined, {len(new)} new, {fixed} fixed) '
          f'in {elapsed:.2f}s [{",".join(pass_names)}]')
    if fixed:
        print('skycheck: baseline has stale entries - shrink it with '
              '--write-baseline')
    return 1 if new else 0


if __name__ == '__main__':
    sys.exit(main())
