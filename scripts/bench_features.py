#!/usr/bin/env python3
"""On-chip microbenchmarks for the r3 serving features.

Measures, on the real chip, with the same 7B-config int8 + fp8-KV
engine the serving benchmarks use:

1. **Prefix KV caching** — TTFT (prefill wall time) for a long-prefix
   prompt with and without the prefix registered.  The reuse path
   forwards only the suffix, so the saving should approach the prefix
   share of prefill compute.
2. **Speculative decoding** — offline throughput and acceptance with
   prompt-lookup drafting vs the windowed decode.  NOTE the honest
   caveat: with random-init weights greedy output collapses to
   repetition, which prompt-lookup predicts almost perfectly — this
   measures the mechanism's UPPER BOUND (the fully-grounded regime),
   not typical open-ended traffic (acceptance ~0 there, and the
   engine's no-draft fallback keeps the windowed path's throughput).

Usage:  python scripts/bench_features.py --out BENCH_FEATURES_r03.json
"""
import argparse
import gc
import json
import statistics
import sys
import time

sys.path.insert(0, '.')


def _engine(draft_len=0, num_slots=16, max_cache_len=512,
            prefill_lanes=4, prefill_chunk=0, kv_block_size=0,
            kv_blocks=None, max_prefixes=16, auto_prefix_cache=False):
    """7B int8 + fp8-KV engine sized for the 16 GB chip: at Hkv=32,
    D=128 a 7B cache row costs ~0.26 MB/token-layer-slot, so slots x
    cache_len is the HBM budget knob (48x512 = the serve-bench shape)."""
    import dataclasses

    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig, InferenceEngine
    from skypilot_tpu.models import get_model_config
    cfg_m = dataclasses.replace(get_model_config('llama2-7b'),
                                weight_dtype='int8')
    cfg = InferConfig(model='llama2-7b', num_slots=num_slots,
                      max_cache_len=max_cache_len, decode_steps=8,
                      cache_dtype=jnp.float8_e4m3fn, draft_len=draft_len,
                      prefill_lanes=prefill_lanes,
                      prefill_chunk=prefill_chunk,
                      kv_block_size=kv_block_size, kv_blocks=kv_blocks,
                      max_prefixes=max_prefixes,
                      auto_prefix_cache=auto_prefix_cache)
    return InferenceEngine(cfg_m, cfg)


def bench_prefix(reps: int = 5):
    import numpy as np

    from skypilot_tpu.infer import Request
    # Long-prompt shape: 4 slots x 1152 cache, single-lane prefill
    # (single-request TTFT; pad lanes would just burn HBM).
    eng = _engine(num_slots=4, max_cache_len=1152, prefill_lanes=1)
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, 32000, size=1024).tolist()
    suffix = rng.integers(0, 32000, size=64).tolist()

    def ttft_ms(tokens):
        times = []
        for _ in range(reps):
            t0 = time.time()
            [res] = eng.generate([Request(tokens=list(tokens),
                                          max_new_tokens=1)])
            times.append((time.time() - t0) * 1000.0)
            assert res.finish_reason == 'length'
        return statistics.median(times)

    # Warm both compile paths outside the measurement.
    eng.generate([Request(tokens=prefix + suffix, max_new_tokens=1)])
    cold = ttft_ms(prefix + suffix)
    eng.register_prefix(prefix)
    eng.generate([Request(tokens=prefix + suffix, max_new_tokens=1)])
    hot = ttft_ms(prefix + suffix)
    hits = eng.prefix_stats['hits']
    del eng
    gc.collect()
    return {
        'prompt_len': len(prefix) + len(suffix),
        'prefix_len': len(prefix),
        'prefill_ms_full': round(cold, 1),
        'prefill_ms_prefix_reuse': round(hot, 1),
        'ttft_reduction': round(1.0 - hot / cold, 3),
        'prefix_hits': hits,
    }


def bench_chunked_prefill(prefill_chunk: int = 64, reps: int = 3):
    """Chunked-prefill cost/benefit at the long-prompt shape: offline
    TTFT for a prompt no bucket holds (chunked engine) vs the same
    prompt through the monolithic auto-appended bucket — the chunked
    path trades a little lone-stream TTFT (per-chunk dispatch overhead)
    for a bounded decode stall (BENCH_MICRO chunk_stall measures that
    side)."""
    import numpy as np

    from skypilot_tpu.infer import Request
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 32000, size=1100).tolist()

    def ttft_ms(eng):
        eng.generate([Request(tokens=list(prompt), max_new_tokens=1)])
        times = []
        for _ in range(reps):
            t0 = time.time()
            [res] = eng.generate([Request(tokens=list(prompt),
                                          max_new_tokens=1)])
            times.append((time.time() - t0) * 1000.0)
            assert res.finish_reason == 'length'
        return statistics.median(times)

    eng = _engine(num_slots=4, max_cache_len=1152, prefill_lanes=1,
                  prefill_chunk=prefill_chunk)
    chunked = ttft_ms(eng)
    stats = dict(eng.chunk_stats)
    del eng
    gc.collect()
    eng = _engine(num_slots=4, max_cache_len=1152, prefill_lanes=1)
    mono = ttft_ms(eng)
    del eng
    gc.collect()
    return {
        'prefill_chunk': prefill_chunk,
        'prompt_len': len(prompt),
        'ttft_ms_chunked': round(chunked, 1),
        'ttft_ms_monolithic': round(mono, 1),
        'ttft_overhead': round(chunked / mono - 1.0, 3),
        'chunk_stats': stats,
    }


def bench_spec(num_requests: int = 32, prompt_len: int = 219,
               new_tokens: int = 188):
    import numpy as np

    from skypilot_tpu.infer import Request

    def run(eng, reqs, label, out):
        # Same measurement shape as engine.benchmark, custom prompts.
        eng.generate([Request(tokens=list(reqs[0].tokens),
                              max_new_tokens=2)])
        eng._warm_spec(len(reqs[0].tokens))
        for k in eng.spec_stats:
            eng.spec_stats[k] = 0
        t0 = time.time()
        results = eng.generate([Request(tokens=list(r.tokens),
                                        max_new_tokens=r.max_new_tokens)
                                for r in reqs])
        elapsed = time.time() - t0
        st = eng.spec_stats
        row = {
            'output_tokens_per_second': round(
                sum(len(r.output_tokens) for r in results) / elapsed, 1),
            'requests_per_second': round(len(results) / elapsed, 2),
            'spec': dict(st),
        }
        if st['drafted']:
            row['accept_rate'] = round(st['accepted'] / st['drafted'], 3)
        if st['dispatches']:
            row['tokens_per_dispatch'] = round(
                1 + st['accepted'] / st['dispatches'], 2)
        out[label] = row

    rng = np.random.default_rng(0)
    random_reqs = [
        Request(tokens=rng.integers(0, 32000, size=prompt_len).tolist(),
                max_new_tokens=new_tokens) for _ in range(num_requests)
    ]
    out = {}
    eng = _engine(draft_len=0)
    run(eng, random_reqs, 'draft_len_0_random', out)
    del eng
    gc.collect()
    eng = _engine(draft_len=4)
    run(eng, random_reqs, 'draft_len_4_random', out)
    out['dispatch_cost'] = bench_dispatch_cost(eng, prompt_len)
    del eng
    gc.collect()
    return out


def bench_dispatch_cost(eng, prompt_len, iters: int = 20):
    """Direct hardware costs of the two decode dispatch shapes, full
    batch: windowed = decode_steps sequential [B,1] forwards per
    dispatch; verify = one [B, 1+D] forward.  The verify dispatch is
    one weight-stream, so speculation wins once expected accepted
    tokens/slot exceed the derived break-even — workload acceptance
    decides (trained grounded traffic; random weights in bf16 flip
    argmax near-ties between the two shapes, so an on-chip oracle
    acceptance run is NOT meaningful and is deliberately absent)."""
    import numpy as np

    from skypilot_tpu.infer import Request
    from skypilot_tpu.infer import engine as engine_mod
    rng = np.random.default_rng(1)
    # Fill every slot with a long-budget request (host-side start only).
    items = []
    for slot in range(eng.cfg.num_slots):
        req = Request(tokens=rng.integers(
            0, 32000, size=prompt_len).tolist(), max_new_tokens=280)
        items.append((req, slot, 0.0, *eng._validate_request(req)))
    eng._start_batch(items)

    def timeit(fn, warm=3):
        for _ in range(warm):
            fn()
        t0 = time.time()
        for _ in range(iters):
            fn()
        # Host sync: the host loop reads tokens back each dispatch, so
        # wall time is already synchronous.
        return (time.time() - t0) * 1000.0 / iters

    win_ms = timeit(eng._decode_step)
    plain = engine_mod.prompt_lookup_draft
    engine_mod.prompt_lookup_draft = \
        lambda hist, k, nmax: [1, 2, 3, 4][:k]

    def spec():
        eng._accept_ema = 1.0     # keep the policy gate open
        eng._spec_step()

    try:
        spec_ms = timeit(spec)
    finally:
        engine_mod.prompt_lookup_draft = plain
    k = eng.cfg.decode_steps
    return {
        'windowed_ms_per_dispatch': round(win_ms, 2),
        'windowed_tokens_per_dispatch': k,
        'verify_ms_per_dispatch': round(spec_ms, 2),
        'windowed_ms_per_token': round(win_ms / k, 3),
        # Verify yields 1+accepted tokens: break-even acceptance per
        # slot for speculation to beat windowed throughput.
        'break_even_accepted_per_slot': round(spec_ms / (win_ms / k) - 1,
                                              2),
    }


def bench_kv_occupancy(block_size: int = 16):
    """Paged KV pool occupancy through one serving episode (stats()):
    after a 1024-token prefix registers, mid-flight with every slot
    decoding a prefix-sharing prompt (shared blocks carry one copy for
    N readers), and after the batch drains (everything back on the free
    list).  The numbers /stats serves — this prints them next to the
    perf sections so a regression in the accounting shows up in the
    bench artifact."""
    import numpy as np

    from skypilot_tpu.infer import Request
    eng = _engine(num_slots=4, max_cache_len=1152, prefill_lanes=1,
                  kv_block_size=block_size)
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, 32000, size=1024).tolist()
    out = {'idle': eng.stats()}
    eng.register_prefix(prefix)
    out['prefix_registered'] = eng.stats()
    # Host-side start only (like bench_dispatch_cost): every slot takes
    # a prefix-sharing prompt, then snapshot mid-flight occupancy.
    items = []
    for slot in range(eng.cfg.num_slots):
        req = Request(tokens=prefix + rng.integers(
            0, 32000, size=32).tolist(), max_new_tokens=64)
        items.append((req, slot, 0.0, *eng._validate_request(req)))
    eng._start_batch(items)
    eng._decode_step()
    out['mid_flight_4_slots_sharing'] = eng.stats()
    for i in range(eng.cfg.num_slots):
        eng._finish_slot(i, 'cancelled')
    out['drained'] = eng.stats()
    del eng
    gc.collect()
    return out


def bench_fault_containment(num_requests: int = 16,
                            prompt_len: int = 128,
                            new_tokens: int = 64):
    """Cost of the fault-tolerance surface, measured on-chip:

    - armed-vs-unarmed decode overhead (the zero-overhead-unarmed
      claim: a plan that never fires should cost one attribute check
      per site consult);
    - containment wall-time: an attributed decode-step fault mid-batch
      fails one request while the survivors run to completion — the
      faulted batch should cost about the same as the clean one, not
      a restart.
    """
    import numpy as np

    from skypilot_tpu.infer import FaultPlan, FaultSpec, Request
    eng = _engine(num_slots=8, max_cache_len=256)
    rng = np.random.default_rng(0)

    def reqs():
        return [Request(tokens=rng.integers(
            0, 32000, size=prompt_len).tolist(),
                        max_new_tokens=new_tokens, request_id=str(i))
                for i in range(num_requests)]

    eng.warmup_decode(reqs()[0].tokens)

    t0 = time.time()
    clean = eng.generate(reqs())
    wall_clean = time.time() - t0
    assert all(r.finish_reason == 'length' for r in clean)

    # Armed but never firing: measures the consult overhead alone.
    eng.arm_faults(FaultPlan(seed=0, specs=[
        FaultSpec(site='decode_step', hits=(10 ** 9,))]))
    t0 = time.time()
    armed = eng.generate(reqs())
    wall_armed = time.time() - t0
    eng.disarm_faults()
    assert all(r.finish_reason == 'length' for r in armed)

    # Attributed mid-batch fault: one request dies, survivors finish.
    eng.arm_faults(FaultPlan(seed=0, specs=[
        FaultSpec(site='decode_step', hits=(3,), slot=1)]))
    t0 = time.time()
    faulted = eng.generate(reqs())
    wall_faulted = time.time() - t0
    eng.disarm_faults()
    failed = [r for r in faulted if r.finish_reason == 'error']
    assert len(failed) == 1 and failed[0].error_class == 'internal'

    return {
        'wall_clean_s': round(wall_clean, 3),
        'wall_armed_unfired_s': round(wall_armed, 3),
        'armed_overhead_pct': round(
            100.0 * (wall_armed - wall_clean) / wall_clean, 2),
        'wall_faulted_s': round(wall_faulted, 3),
        'failed_requests': len(failed),
        'survivors_completed': sum(
            1 for r in faulted if r.finish_reason == 'length'),
        'counters': dict(eng.fault_stats),
    }


def bench_radix(reps: int = 5):
    """Automatic radix prefix caching at the shared-system-prompt
    shape: every request carries the same 512-token system prompt plus
    a distinct 64-token user turn.  Compares TTFT for unrelated
    prompts (no match possible — the full-prefill baseline, lookups
    included) against system-prompt prompts once earlier traffic has
    warmed the tree, and reports the tree's hit-rate.  Nothing is
    registered explicitly: the whole saving comes from automatic
    insertion on completion + longest-block-prefix match on admission."""
    import numpy as np

    from skypilot_tpu.infer import Request
    eng = _engine(num_slots=4, max_cache_len=1152, prefill_lanes=1,
                  kv_block_size=16, auto_prefix_cache=True)
    rng = np.random.default_rng(0)
    system = rng.integers(0, 32000, size=512).tolist()

    def fresh():
        return rng.integers(0, 32000, size=576).tolist()

    def turn():
        return system + rng.integers(0, 32000, size=64).tolist()

    def ttft_ms(make):
        times = []
        for _ in range(reps):
            t0 = time.time()
            [res] = eng.generate([Request(tokens=make(),
                                          max_new_tokens=1)])
            times.append((time.time() - t0) * 1000.0)
            assert res.finish_reason == 'length'
        return statistics.median(times)

    eng.generate([Request(tokens=fresh(), max_new_tokens=1)])  # compile
    cold = ttft_ms(fresh)          # no shared prefix: full prefill
    eng.generate([Request(tokens=turn(), max_new_tokens=1)])   # insert
    eng.generate([Request(tokens=turn(), max_new_tokens=1)])   # sb warm
    hot = ttft_ms(turn)            # 512/576 tokens reused by refcount
    st = eng.stats()['kv']['radix']
    del eng
    gc.collect()
    return {
        'prompt_len': 576,
        'system_prompt_len': 512,
        'ttft_ms_no_overlap': round(cold, 1),
        'ttft_ms_shared_system_prompt': round(hot, 1),
        'ttft_reduction': round(1.0 - hot / cold, 3),
        'radix_hit_rate': round(st['hit_rate'], 3),
        'radix_hits': st['hits'],
        'radix_tokens_reused': st['tokens_reused'],
        'radix_nodes': st['nodes'],
        'radix_evictions': st['evictions'],
    }


def bench_lb_affinity(n_replicas_sweep=(1, 2, 4, 8), groups: int = 31,
                      per_group: int = 16, prompt_blocks: int = 24,
                      shared_blocks: int = 12):
    """Policy-level fleet-cache simulation (no jax, no engines): replay
    a grouped-prompt trace through the real LB policy objects, modelling
    each replica's radix tree as an LRU set of block-aligned prefixes
    with FIXED per-replica capacity (~40% of the fleet working set —
    one replica cannot hold every prefix family).  Shows the mechanism
    the serve-plane bench measures end-to-end: under load-only routing
    every replica eventually sees every group, so each cache thrashes
    over the full working set, while prefix_affinity partitions the
    key space so each replica only holds its ~1/N share — fleet hit
    rate GROWS with N instead of decaying.  groups is odd on purpose:
    groups % n == 0 would hand round_robin perfect accidental affinity.
    (In this zero-concurrency replay least_load degenerates to
    always-first-replica — best case for it, and still capped at one
    replica's capacity; the end-to-end bench covers the concurrent
    case where it spreads.)"""
    import random

    from skypilot_tpu.serve.load_balancing_policies import (
        LoadBalancingPolicy, RequestContext)
    block = 16
    # Symbolic prefix keys: cache identity only needs (group, depth)
    # for the shared head and (group, rep, depth) past it — hashing
    # real 100s-of-token tuples would dominate the runtime.
    contexts, keys = {}, {}
    for g in range(groups):
        head = [(g * 131 + 7 * j) % 97 + 1
                for j in range(shared_blocks * block)]
        for r in range(per_group):
            tail = [(g * 17 + r * 29 + 3 * j) % 97 + 1
                    for j in range((prompt_blocks - shared_blocks) * block)]
            contexts[g, r] = RequestContext(tokens=head + tail,
                                            adapter=None)
            keys[g, r] = ([('s', g, d) for d in range(1, shared_blocks + 1)]
                          + [('t', g, r, d)
                             for d in range(shared_blocks + 1,
                                            prompt_blocks + 1)])
    order = [(g, r) for r in range(per_group) for g in range(groups)]
    random.Random(0).shuffle(order)
    cap = int(0.4 * groups * prompt_blocks)
    rows = []
    for n in n_replicas_sweep:
        urls = [f'http://10.0.0.{i + 1}:8000' for i in range(n)]
        row = {'n_replicas': n}
        for name in ('round_robin', 'least_load', 'prefix_affinity'):
            policy = LoadBalancingPolicy.make(name)
            policy.set_ready_replicas(urls)
            caches = {u: {} for u in urls}   # prefix-key -> lru tick
            tick = 0
            hit_tokens = total_tokens = 0
            for g, r in order:
                pick = policy.select_replica(context=contexts[g, r])
                cache = caches[pick]
                depth = 0
                for key in keys[g, r]:
                    if key not in cache:
                        break
                    depth += 1
                hit_tokens += depth * block
                total_tokens += prompt_blocks * block
                for key in keys[g, r]:
                    tick += 1
                    cache[key] = tick
                while len(cache) > cap:
                    victim = min(cache, key=cache.get)
                    del cache[victim]
                policy.request_done(pick)
            row[name] = round(hit_tokens / total_tokens, 3)
        ll = row['least_load']
        row['affinity_vs_least_load'] = (round(row['prefix_affinity'] / ll, 2)
                                         if ll > 1e-3 else None)
        rows.append(row)
    return {'groups': groups, 'per_group': per_group,
            'prompt_blocks': prompt_blocks, 'shared_blocks': shared_blocks,
            'replica_cache_capacity_blocks': cap,
            'metric': 'fleet_prefix_hit_rate (cached tokens / prompt '
                      'tokens, LRU-capped replica caches)',
            'rows': rows}


def bench_tp_capacity(tp_sweep=(1, 2, 4, 8), hbm_gb=16.0,
                      weights_gb=7.0, block_size=16, typical_len=256,
                      max_cache_len=512):
    """Model-free TP capacity section (no jax, no engines): the
    head-sharded paged pool's fleet economics at 7B geometry.  A tp
    replica owns tp chips; pool pages shard P(None, kv_heads, None,
    None) so its KV budget is the whole slice's HBM minus ONE (sharded)
    weight copy, while per-chip KV read bytes per decode step fall as
    1/tp.  The tradeoff this quantifies: tp chips spent on ONE tp
    replica buy MORE concurrent slots than the same chips spent on tp
    single-chip DP replicas (the weight copies they'd each carry become
    pool), at the price of per-replica all-reduce latency — the serve
    plane lets both coexist behind one LB (BENCH_MICRO_r09 has the
    measured tp=2 identity/dispatch sweep)."""
    # 7B fp8-KV geometry: Hkv=32, D=128, 32 layers, 1-byte cache rows.
    hkv, d, layers, itemsize = 32, 128, 32, 1
    row_bytes = 2 * hkv * d * itemsize * layers
    blocks_per_slot = -(-typical_len // block_size)
    nb = 1
    while nb < blocks_per_slot and nb < max_cache_len // block_size:
        nb *= 2
    full_read = nb * block_size * row_bytes
    rows = []
    base = None
    for tp in tp_sweep:
        if hkv % tp:
            rows.append({'tp': tp, 'supported': False})
            continue
        kv_budget = int(tp * hbm_gb * (1 << 30)) - \
            int(weights_gb * (1 << 30))
        slots_tp = int(kv_budget // (block_size * row_bytes)
                       // blocks_per_slot)
        if base is None:
            base = max(slots_tp, 1)
        # Same tp chips as independent single-chip DP replicas: each
        # carries its own full weight copy.
        dp_budget = int(hbm_gb * (1 << 30)) - int(weights_gb * (1 << 30))
        slots_dp = tp * int(dp_budget // (block_size * row_bytes)
                            // blocks_per_slot)
        rows.append({
            'tp': tp,
            'per_chip_kv_read_bytes_per_step': full_read // tp,
            'slots_one_tp_replica': slots_tp,
            'slots_tp_single_chip_dp_replicas': slots_dp,
            'tp_vs_dp_slot_gain': round(slots_tp / max(slots_dp, 1), 2),
            'capacity_gain_vs_tp1': round(slots_tp / base, 2),
        })
    return {'hbm_gb_per_chip': hbm_gb, 'weights_gb': weights_gb,
            'block_size': block_size, 'typical_resident_len': typical_len,
            'kv_row_bytes': row_bytes,
            'metric': 'max concurrent slots from the paged-pool block '
                      'budget (typical resident length per slot)',
            'rows': rows}


def bench_qos_scheduler(backlog: int = 2000, reps: int = 3):
    """Scheduler-level QoS microbench (no jax, no engines): replay a
    synthetic 2x-overload trace through the real FifoScheduler and
    WfqScheduler objects.  Three numbers: (a) interactive jump-ahead —
    queue positions an interactive arrival waits behind when it lands
    on a full batch backlog (FIFO: the whole backlog; WFQ strict
    priority: 0); (b) admission share under saturation for tenants
    with 3:1:1 weights — WFQ tracks the weights while FIFO hands the
    flooding tenant the share of its arrival rate; (c) raw push+pop
    throughput so the WFQ virtual-time bookkeeping is shown to be
    noise next to a single prefill."""
    import time
    from types import SimpleNamespace

    from skypilot_tpu.infer.qos import WfqScheduler
    from skypilot_tpu.infer.scheduler import FifoScheduler

    def req(tenant, priority='batch', cost=128):
        return SimpleNamespace(tokens=[1] * (cost - 1), max_new_tokens=1,
                               priority=priority, tenant_id=tenant)

    def make_wfq():
        return WfqScheduler(weights={'gold': 3.0, 'silver': 1.0,
                                     'bronze': 1.0})

    # (a) jump-ahead: backlog batch requests queued, then 1 interactive.
    jump = {}
    for name, sched in (('fifo', FifoScheduler()), ('wfq', make_wfq())):
        for i in range(backlog):
            sched.push(req('bronze'))
        sched.push(req('gold', priority='interactive'))
        pos = 0
        while True:
            r = sched.pop()
            if r.priority == 'interactive':
                break
            pos += 1
        jump[name] = pos
    # (b) saturation fairness: bronze floods 2x the arrival rate of
    # gold/silver (the overload), scheduler drains a fixed admission
    # window; share of admitted cost per tenant.
    share = {}
    for name, sched in (('fifo', FifoScheduler()), ('wfq', make_wfq())):
        order = []
        for i in range(backlog):
            order.append(req('gold'))
            order.append(req('silver'))
            order.append(req('bronze'))
            order.append(req('bronze'))
        for r in order:
            sched.push(r)
        admitted = {}
        for _ in range(backlog):          # drain 1/4 of the backlog
            r = sched.pop()
            admitted[r.tenant_id] = admitted.get(r.tenant_id, 0) + 1
        total = sum(admitted.values())
        share[name] = {t: round(n / total, 3)
                       for t, n in sorted(admitted.items())}
    # (c) push+pop throughput.
    thr = {}
    for name, make in (('fifo', FifoScheduler), ('wfq', make_wfq)):
        best = 0.0
        for _ in range(reps):
            sched = make()
            t0 = time.perf_counter()
            for i in range(backlog):
                sched.push(req(('gold', 'silver', 'bronze')[i % 3]))
            while sched.pop() is not None:
                pass
            dt = time.perf_counter() - t0
            best = max(best, 2 * backlog / dt)
        thr[name] = round(best)
    return {
        'backlog': backlog,
        'weights': {'gold': 3.0, 'silver': 1.0, 'bronze': 1.0},
        'interactive_waits_behind': jump,
        'admission_share_bronze_floods_2x': share,
        'push_pop_ops_per_s': thr,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=None)
    ap.add_argument('--reps', type=int, default=5)
    ap.add_argument('--prefill-chunk', type=int, default=64,
                    help='chunk size for the chunked-prefill TTFT '
                         'comparison (0 skips it)')
    ap.add_argument('--qos-only', action='store_true',
                    help='run only the model-free qos scheduler '
                         'section (no jax; CPU-friendly) and merge it '
                         'into --out')
    args = ap.parse_args()
    if args.qos_only:
        qos = bench_qos_scheduler()
        print(json.dumps(qos))
        if args.out:
            try:
                doc = json.load(open(args.out))
            except (FileNotFoundError, ValueError):
                doc = {}
            doc['qos_scheduler'] = qos
            with open(args.out, 'w') as f:
                json.dump(doc, f, indent=2)
            print(f'wrote {args.out}')
        return
    result = {
        'description':
            'r3 serving-feature microbenchmarks on one v5e chip '
            '(llama2-7b config, int8 weights, fp8 KV). prefix_cache: '
            'prefill wall-time for a 1088-token prompt, full vs '
            'suffix-only over a 1024-token registered prefix. '
            'speculative: offline throughput, draft_len 4 vs windowed '
            'decode; random-init greedy output is repetitive, so the '
            'acceptance here is the grounded-regime UPPER BOUND, not '
            'open-ended traffic.',
        'prefix_cache': bench_prefix(reps=args.reps),
    }
    print(json.dumps(result['prefix_cache']))
    result['speculative'] = bench_spec()
    print(json.dumps(result['speculative']))
    if args.prefill_chunk:
        result['chunked_prefill'] = bench_chunked_prefill(
            prefill_chunk=args.prefill_chunk, reps=max(3, args.reps // 2))
        print(json.dumps(result['chunked_prefill']))
    result['kv_occupancy'] = bench_kv_occupancy()
    print(json.dumps(result['kv_occupancy']))
    result['fault_containment'] = bench_fault_containment()
    print(json.dumps(result['fault_containment']))
    result['radix_prefix_cache'] = bench_radix(reps=args.reps)
    print(json.dumps(result['radix_prefix_cache']))
    result['lb_affinity'] = bench_lb_affinity()
    print(json.dumps(result['lb_affinity']))
    result['tp_capacity'] = bench_tp_capacity()
    print(json.dumps(result['tp_capacity']))
    result['qos_scheduler'] = bench_qos_scheduler()
    print(json.dumps(result['qos_scheduler']))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(result, f, indent=2)
        print(f'wrote {args.out}')


if __name__ == '__main__':
    main()
