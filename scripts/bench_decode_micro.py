#!/usr/bin/env python3
"""Decode-dispatch microbenchmark: where does TPOT actually go?

Separates, on the real chip, the three components of the serving
engine's inter-token latency (VERDICT r3 weak #2: TPOT p50 ~80-100 ms
through the plane vs the reference anchor's 18.9 ms on 8x v6e):

  1. pure device time per decode step  — chain M dispatches, sync once;
  2. production dispatch time          — per-dispatch host transfer of
     the [K, B] token block, exactly what _decode_step does;
  3. prefill dispatch time per bucket  — the TTFT device component.

(2) - (1) is the host<->device round-trip tax (on a tunneled chip this
is the dominant suspect).  Fitting time(K) = F + K*s over K in
{1,2,4,8,16} gives the fixed-overhead F and marginal per-step cost s:
TPOT at window K is (F + K*s)/K = s + F/K, which says exactly how much
window amortization the tunnel forces.

Usage (on the TPU host):
  python scripts/bench_decode_micro.py [--model llama2-7b]
      [--num-slots 16] [--max-cache-len 512] [--reps 20]

--paged mode (CPU-dryrun safe): the block-paged KV cache's bandwidth
and capacity story instead of the dispatch-cost fit.  Per decode step a
dense slot streams max_cache_len KV rows regardless of fill; a paged
slot streams ceil(len/block)*block rows (power-of-two-bucketed table
widths round that up at most 2x, still length-proportional).  Reports,
at the target model's geometry: the analytic bytes/FLOPs-per-step sweep
over filled lengths, the max-concurrent-slot capacity model at a fixed
HBM budget, and a MEASURED tiny-model dense-vs-paged decode dispatch
sweep (CPU: direction-of-effect anchor; on chip: real TPOT).

  python scripts/bench_decode_micro.py --paged --out BENCH_MICRO_r07.json

--radix mode (CPU-dryrun safe): TTFT vs prefix-overlap fraction with
automatic radix prefix caching on vs off.  A family of prompts shares
its first overlap*L tokens; with the tree warm, the radix engine
prefills only the (1 - overlap) suffix (bucketed), so both the
analytic prefill compute and the measured TTFT fall with overlap.

  python scripts/bench_decode_micro.py --radix --out BENCH_MICRO_r08.json

--tp mode (CPU-dryrun safe): the head-sharded paged pool's
tensor-parallel scaling story.  The pool pages carry
P(None, 'kv_heads', None, None): each chip holds Hkv/tp heads of every
block, so per-chip KV read bytes per decode step fall as 1/tp while the
replica's pool block budget (and with it max concurrent slots) grows
~linearly in tp — the analytic sweep quantifies both at the target
model's geometry, and the measured tiny-model sweep drives the REAL
single-chip vs tp=2 paged decode roots and checks greedy identity.

  python scripts/bench_decode_micro.py --tp --out BENCH_MICRO_r09.json

--kv-tier mode (CPU-dryrun safe): the host-RAM KV tier's restore
economics at working sets larger than the device pool.  The analytic
sweep models, at the target geometry, the hot-set fraction each tier
covers (device pool, host tier at --host-kv-gb, miss) and the cost of
a tier restore (H2D bytes over --h2d-gbps, overlapped with the
suffix-only prefill) vs a full re-prefill of the evicted prefix.  The
measured tiny-model sweep cycles prefix families through a
deliberately small device pool at 2-8x its capacity, tier on vs off,
and times round-2 hot re-references: tier-off pays the full monolithic
re-prefill, tier-on restores the spilled blocks and prefills only the
suffix bucket.

  python scripts/bench_decode_micro.py --kv-tier --out BENCH_MICRO_r10.json
"""
import argparse
import dataclasses
import json
import sys
import time

sys.path.insert(0, '.')


def _pow2_bucket(n: int, cap: int) -> int:
    nb = 1
    while nb < n and nb < cap:
        nb *= 2
    return min(nb, cap)


def paged_report(args):
    """--paged mode: analytic sweep + capacity model + tiny measured
    sweep.  Runs without building the target model (geometry only), so
    it works on the 1-CPU dryrun container at 7B scale."""
    import numpy as np

    from skypilot_tpu.infer.engine import resolve_cache_dtype
    from skypilot_tpu.models import get_model_config

    mc = get_model_config(args.model)
    m = args.max_cache_len
    bs = args.block_size
    dt = np.dtype(resolve_cache_dtype(args.cache_dtype))
    # One token's K+V across all layers.
    row_bytes = 2 * mc.num_kv_heads * mc.head_dim_ * dt.itemsize * \
        mc.num_layers
    hq = mc.num_heads
    fills = [f for f in args.fill_sweep if f < m] + [m - 1]
    sweep = []
    for fill in fills:
        blocks = -(-(fill + 1) // bs)
        nb = _pow2_bucket(blocks, m // bs)
        # Per decode step, per slot: KV rows streamed by the attention
        # (the HBM-bound term) and the score/value FLOPs over them.
        row = {
            'filled_len': fill,
            'dense_rows_per_step': m,
            'paged_rows_exact': blocks * bs,
            'paged_rows_bucketed': nb * bs,
            'dense_kv_bytes_per_step': m * row_bytes,
            'paged_kv_bytes_per_step': nb * bs * row_bytes,
            'kv_read_reduction': round(m / (nb * bs), 2),
            # 2 matmuls (scores + values), 2 flops/MAC, all q heads.
            'dense_attn_flops_per_step':
                2 * 2 * hq * mc.head_dim_ * m * mc.num_layers,
            'paged_attn_flops_per_step':
                2 * 2 * hq * mc.head_dim_ * nb * bs * mc.num_layers,
        }
        sweep.append(row)
        print(f'fill={fill:4d}: dense reads {m:4d} rows/step, paged '
              f'{nb * bs:4d} ({row["kv_read_reduction"]:.2f}x less)',
              flush=True)
    # Capacity model: max concurrent slots at a fixed KV HBM budget.
    # Dense reserves max_cache_len rows per slot up front; paged holds
    # ceil(len/block) blocks per slot, so capacity depends on the
    # lengths actually resident.  typical_len: the steady-state resident
    # length (prompt + half the generation budget is the serve-bench
    # expectation).
    kv_budget = int((args.hbm_gb - args.weights_gb) * (1 << 30))
    dense_slots = kv_budget // (m * row_bytes)
    pool_blocks = kv_budget // (bs * row_bytes)
    typical = args.typical_len
    blocks_per_slot = -(-typical // bs)
    paged_slots = pool_blocks // blocks_per_slot
    capacity = {
        'hbm_budget_gb': args.hbm_gb,
        'weights_gb': args.weights_gb,
        'kv_budget_bytes': kv_budget,
        'kv_row_bytes': row_bytes,
        'block_size': bs,
        'typical_resident_len': typical,
        'max_slots_dense': int(dense_slots),
        'max_slots_paged': int(paged_slots),
        'capacity_gain': round(paged_slots / max(dense_slots, 1), 2),
    }
    print(f'capacity @ {args.hbm_gb:.0f} GB HBM ({args.weights_gb:.0f} '
          f'GB weights): dense {dense_slots} slots, paged {paged_slots} '
          f'({capacity["capacity_gain"]:.2f}x) at typical resident len '
          f'{typical}', flush=True)

    measured = None
    if not args.no_measure:
        measured = _measure_tiny_sweep(args, fills)
    out = {
        'description':
            f'paged-KV decode bandwidth/capacity model at {args.model} '
            f'geometry (Hkv={mc.num_kv_heads}, D={mc.head_dim_}, '
            f'layers={mc.num_layers}, {dt.name} cache). Analytic '
            'bytes/FLOPs per decode step per slot: dense streams '
            'max_cache_len rows regardless of fill; paged streams the '
            'power-of-two-bucketed ceil(len/block)*block rows. '
            'measured_tiny_sweep times REAL dense vs paged decode '
            'dispatches on a 2-layer toy model on the current backend '
            '(CPU dryrun: direction-of-effect, not chip TPOT).',
        'model': args.model,
        'max_cache_len': m,
        'block_size': bs,
        'filled_len_sweep': sweep,
        'capacity_model': capacity,
        'measured_tiny_sweep': measured,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(out, f, indent=2)
        print(f'wrote {args.out}')


def _measure_tiny_sweep(args, fills, steps=4, reps=5):
    """Dense vs paged decode dispatch wall time on a tiny llama at each
    filled length — the measured counterpart of the analytic sweep.
    Uses the engine's own jitted paths (same code serving runs)."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.analysis import sanitizers
    from skypilot_tpu.infer import InferConfig, InferenceEngine
    from skypilot_tpu.models.llama import LlamaConfig

    m = args.max_cache_len
    bs = args.block_size
    b = 8
    cfg_m = LlamaConfig(name='paged-micro', vocab_size=256,
                        hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        max_seq_len=m, tie_embeddings=True,
                        dtype='float32')
    common = dict(num_slots=b, max_cache_len=m, prefill_buckets=(64,),
                  decode_steps=steps, cache_dtype=jnp.float32)
    dense = InferenceEngine(cfg_m, InferConfig(**common))
    paged = InferenceEngine(cfg_m, InferConfig(kv_block_size=bs,
                                               **common),
                            params=dense.params)
    tokens = jnp.ones((b,), jnp.int32)
    temps = jnp.zeros((b,), jnp.float32)
    adapters = jnp.full((b,), -1, jnp.int32)
    key = jax.random.PRNGKey(0)
    rows = []
    for fill in fills:
        lengths = jnp.full((b,), fill, jnp.int32)

        def timed(dispatch):
            toks, cache = dispatch()
            _ = float(toks[0, 0, 0])             # compile + sync
            t0 = time.time()
            for _ in range(reps):
                toks, cache = dispatch()
                _ = float(toks[0, 0, 0])
            return (time.time() - t0) / reps * 1e3

        def d_dense():
            out = dense._decode(dense.params, dense.cache, tokens,
                                lengths, temps, key, adapters, steps)
            dense.cache = out[3]
            return out[0], out[3]

        for i in range(b):
            paged._ensure_blocks(i, min(fill + steps, m))
        nb = paged._nb_bucket(-(-(fill + steps) // bs))
        tables = paged._lane_tables(range(b), nb)

        def d_paged():
            out = paged._paged_decode(paged.params, paged.cache, tokens,
                                      lengths, temps, key, adapters,
                                      tables, steps)
            paged.cache = out[3]
            return out[0], out[3]

        dms = timed(d_dense)
        pms = timed(d_paged)
        for i in range(b):
            paged._free_slot_blocks(i)
        rows.append({'filled_len': fill, 'table_blocks': int(nb),
                     'dense_dispatch_ms': round(dms, 2),
                     'paged_dispatch_ms': round(pms, 2),
                     'dense_tpot_ms': round(dms / steps, 3),
                     'paged_tpot_ms': round(pms / steps, 3)})
        print(f'measured fill={fill:4d}: dense {dms:7.2f} ms, paged '
              f'{pms:7.2f} ms ({nb} blocks gathered)', flush=True)
    if sanitizers.compile_sanitizer_enabled():
        # The sweep drives the real jit roots across the whole nb
        # ladder: accumulated compiles must stay within the provable
        # static bounds for these configs.
        for eng in (dense, paged):
            counts = sanitizers.check_compile_budget(eng)
            touched = {k: v for k, v in counts.items() if v[0]}
            print(f'compile budget ok: '
                  f'{ {k: f"{m}/{bd}" for k, (m, bd) in touched.items()} }',
                  flush=True)
    if sanitizers.shard_sanitizer_enabled():
        # The sweep's engines keep their root inputs (params, cache)
        # live the whole run: their committed layouts must still match
        # the declared registry (no-op off-mesh).
        for eng in (dense, paged):
            report = sanitizers.check_shard_layout(eng)
            print(f'shard layout ok: {report}', flush=True)
    return {'batch': b, 'decode_steps': steps,
            'model': 'tiny 2-layer llama (float32)', 'rows': rows}


def tp_report(args):
    """--tp mode: analytic per-chip KV bandwidth + replica capacity vs
    tensor degree at the target geometry, plus a measured tiny-model
    single-chip vs tp=2 paged sweep on the current backend."""
    import numpy as np

    from skypilot_tpu.infer.engine import resolve_cache_dtype
    from skypilot_tpu.models import get_model_config

    mc = get_model_config(args.model)
    m = args.max_cache_len
    bs = args.block_size
    dt = np.dtype(resolve_cache_dtype(args.cache_dtype))
    row_bytes = 2 * mc.num_kv_heads * mc.head_dim_ * dt.itemsize * \
        mc.num_layers
    typical = args.typical_len
    blocks_per_slot = -(-typical // bs)
    nb = _pow2_bucket(blocks_per_slot, m // bs)
    # Per decode step, per slot, a chip gathers its Hkv/tp heads of the
    # bucketed ceil(len/block)*block rows: the HBM-bound attention term.
    full_read = nb * bs * row_bytes
    weights_bytes = int(args.weights_gb * (1 << 30))
    sweep = []
    base_slots = None
    for tp in args.tp_sweep:
        if mc.num_kv_heads % tp:
            sweep.append({'tp': tp, 'supported': False,
                          'reason': f'num_kv_heads {mc.num_kv_heads} % '
                                    f'{tp} != 0'})
            continue
        # A tp-replica owns tp chips: weights shard over all of them
        # (weights_gb total, 1/tp per chip) and the pool pages shard on
        # kv_heads, so the replica's KV budget is the whole slice's HBM
        # minus ONE copy of the weights.
        kv_budget = int(tp * args.hbm_gb * (1 << 30)) - weights_bytes
        pool_blocks = kv_budget // (bs * row_bytes)
        slots = int(pool_blocks // blocks_per_slot)
        if base_slots is None:
            base_slots = max(slots, 1)
        row = {
            'tp': tp,
            'supported': True,
            'per_chip_kv_read_bytes_per_step': full_read // tp,
            'kv_read_fraction_of_tp1': round(1.0 / tp, 4),
            'per_chip_weights_bytes': weights_bytes // tp,
            'replica_kv_budget_bytes': kv_budget,
            'pool_blocks': int(pool_blocks),
            'max_slots_paged': slots,
            'capacity_gain_vs_tp1': round(slots / base_slots, 2),
        }
        sweep.append(row)
        print(f'tp={tp}: per-chip KV read {full_read // tp:>12d} B/step '
              f'(1/{tp} of tp=1), {slots:5d} slots at typical len '
              f'{typical} ({row["capacity_gain_vs_tp1"]:.2f}x)',
              flush=True)

    measured = None
    if not args.no_measure:
        measured = _measure_tp_sweep(args)
    out = {
        'description':
            f'Head-sharded paged KV pool vs tensor degree at '
            f'{args.model} geometry (Hkv={mc.num_kv_heads}, '
            f'D={mc.head_dim_}, layers={mc.num_layers}, {dt.name} '
            'cache). Pool pages carry P(None, kv_heads, None, None): '
            'per-chip KV read bytes per decode step scale 1/tp (each '
            'chip gathers only its Hkv/tp heads, chip-local), and the '
            'replica KV budget is the whole slice HBM minus one '
            '(sharded) weight copy, so slot capacity grows ~linearly '
            'in tp. measured_tiny_sweep drives the REAL single-chip '
            'vs tp=2 paged decode roots on the current backend and '
            'checks greedy identity (CPU dryrun: direction-of-effect, '
            'not chip TPOT).',
        'model': args.model,
        'max_cache_len': m,
        'block_size': bs,
        'typical_resident_len': typical,
        'hbm_gb_per_chip': args.hbm_gb,
        'weights_gb': args.weights_gb,
        'kv_row_bytes': row_bytes,
        'tp_sweep': sweep,
        'measured_tiny_sweep': measured,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(out, f, indent=2)
        print(f'wrote {args.out}')


def _measure_tp_sweep(args, steps=4, reps=5):
    """Single-chip vs tp=2 paged decode dispatch on a tiny llama: the
    measured counterpart of the analytic tp sweep, through the SAME
    jitted roots serving uses.  Also asserts greedy identity and the
    per-chip pool accounting."""
    import jax

    if jax.device_count() < 2:
        print('tp measured sweep skipped: <2 devices', flush=True)
        return None

    import jax.numpy as jnp

    from skypilot_tpu.analysis import sanitizers
    from skypilot_tpu.infer import InferConfig, InferenceEngine, Request
    from skypilot_tpu.models.llama import LlamaConfig
    from skypilot_tpu.parallel import tp_mesh

    m = min(args.max_cache_len, 256)
    bs = args.block_size
    b = 8
    cfg_m = LlamaConfig(name='tp-micro', vocab_size=256,
                        hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        max_seq_len=m, tie_embeddings=True,
                        dtype='float32')
    common = dict(num_slots=b, max_cache_len=m, prefill_buckets=(64,),
                  decode_steps=steps, cache_dtype=jnp.float32,
                  kv_block_size=bs, max_new_tokens=8)
    single = InferenceEngine(cfg_m, InferConfig(**common))
    tp = InferenceEngine(cfg_m, InferConfig(**common),
                         params=single.params, mesh=tp_mesh(2))
    # Greedy identity through the full paged path.
    reqs = [Request(tokens=[3 + i, 7, 11, 2 * i + 1], max_new_tokens=6)
            for i in range(4)]
    import copy as _copy
    out_s = single.generate([_copy.deepcopy(r) for r in reqs])
    out_t = tp.generate([_copy.deepcopy(r) for r in reqs])
    identical = all(a.output_tokens == c.output_tokens
                    for a, c in zip(out_s, out_t))
    assert identical, 'tp=2 greedy stream diverged from single-chip'
    print(f'greedy identity tp=2 vs single-chip: ok '
          f'({len(reqs)} requests)', flush=True)

    tokens = jnp.ones((b,), jnp.int32)
    temps = jnp.zeros((b,), jnp.float32)
    adapters = jnp.full((b,), -1, jnp.int32)
    key = jax.random.PRNGKey(0)
    fill = min(args.typical_len, m - steps - 1)
    lengths = jnp.full((b,), fill, jnp.int32)
    rows = []
    for name, eng in (('single', single), ('tp2', tp)):
        for i in range(b):
            eng._ensure_blocks(i, min(fill + steps, m))
        nb = eng._nb_bucket(-(-(fill + steps) // bs))
        tables = eng._lane_tables(range(b), nb)

        def dispatch():
            out = eng._paged_decode(eng.params, eng.cache, tokens,
                                    lengths, temps, key, adapters,
                                    tables, steps)
            eng.cache = out[3]
            return out[0]

        _ = float(dispatch()[0, 0, 0])           # compile + sync
        t0 = time.time()
        for _ in range(reps):
            _ = float(dispatch()[0, 0, 0])
        ms = (time.time() - t0) / reps * 1e3
        for i in range(b):
            eng._free_slot_blocks(i)
        kv = eng.stats()['kv']
        rows.append({'engine': name, 'tp': kv['tp'],
                     'dispatch_ms': round(ms, 2),
                     'tpot_ms': round(ms / steps, 3),
                     'pool_bytes_total': kv['bytes']['total'],
                     'pool_bytes_per_chip': kv['bytes']['per_chip_total']})
        print(f'measured {name}: {ms:7.2f} ms/dispatch, pool '
              f'{kv["bytes"]["per_chip_total"]} B/chip', flush=True)
    assert rows[1]['pool_bytes_per_chip'] * 2 == rows[1]['pool_bytes_total']
    if sanitizers.shard_sanitizer_enabled():
        for eng in (single, tp):
            report = sanitizers.check_shard_layout(eng)
            print(f'shard layout ok: {report}', flush=True)
    if sanitizers.compile_sanitizer_enabled():
        for eng in (single, tp):
            counts = sanitizers.check_compile_budget(eng)
            touched = {k: v for k, v in counts.items() if v[0]}
            print(f'compile budget ok: '
                  f'{ {k: f"{mm}/{bd}" for k, (mm, bd) in touched.items()} }',
                  flush=True)
    return {'batch': b, 'decode_steps': steps, 'filled_len': fill,
            'greedy_identity': identical,
            'model': 'tiny 2-layer llama (float32)', 'rows': rows}


def radix_report(args):
    """--radix mode: measured TTFT sweep vs prefix-overlap fraction on
    a tiny model, radix caching on vs off, plus the analytic
    suffix-only prefill model.  CPU dryrun gives direction-of-effect;
    on chip the same sweep gives real TTFT."""
    import random as pyrandom

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig, InferenceEngine, Request
    from skypilot_tpu.models.llama import LlamaConfig

    L = 64                     # prompt length; overlap = shared/L
    bs = 8
    m = 128
    cfg_m = LlamaConfig(name='radix-micro', vocab_size=256,
                        hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        max_seq_len=m, tie_embeddings=True,
                        dtype='float32')
    common = dict(num_slots=4, max_cache_len=m,
                  prefill_buckets=(8, 16, 32, 64), max_new_tokens=4,
                  cache_dtype=jnp.float32)
    off = InferenceEngine(cfg_m, InferConfig(kv_block_size=bs, **common))
    on = InferenceEngine(cfg_m, InferConfig(kv_block_size=bs,
                                            auto_prefix_cache=True,
                                            **common),
                         params=off.params)
    # Deterministic warmup: the same helper serve-plane boots use
    # compiles every prefill/suffix bucket up front, so per-row
    # warming only has to seed the radix tree, not the jit cache.
    off.warmup()
    on.warmup()
    r = pyrandom.Random(0)
    shared_full = [r.randrange(1, 256) for _ in range(L)]
    reps = args.reps if args.reps < 20 else 8

    def ttft_ms(eng, prompts):
        # Per-request single-token generate: prefill + 1 decode, the
        # TTFT shape.  The first call seeds the shared prefix into
        # the tree (compiles are already warm via warmup()).
        for p in prompts[:1]:
            eng.generate([Request(tokens=list(p), max_new_tokens=1)])
        times = []
        for p in prompts[1:]:
            t0 = time.time()
            eng.generate([Request(tokens=list(p), max_new_tokens=1)])
            times.append(time.time() - t0)
        times.sort()
        return times[len(times) // 2] * 1e3

    sweep = []
    for overlap in (0.0, 0.25, 0.5, 0.75):
        shared_len = int(L * overlap) // bs * bs
        shared = shared_full[:shared_len]
        prompts = [shared + [r.randrange(1, 256)
                             for _ in range(L - shared_len)]
                   for _ in range(reps + 2)]
        suffix = L - shared_len
        sb = next(k for k in common['prefill_buckets'] if k >= max(suffix, 1))
        hits0 = on.radix_stats['hits']
        reused0 = on.radix_stats['tokens_reused']
        # Warm the tree with the shared prefix before timing the
        # radix engine (the first prompt inserts it on completion).
        off_ms = ttft_ms(off, prompts)
        on_ms = ttft_ms(on, prompts)
        row = {
            'overlap': overlap,
            'shared_tokens': shared_len,
            'suffix_tokens': suffix,
            'prefill_tokens_baseline': L,
            'prefill_tokens_radix': sb,
            'prefill_compute_fraction': round(sb / L, 3),
            'ttft_ms_radix_off': round(off_ms, 2),
            'ttft_ms_radix_on': round(on_ms, 2),
            'ttft_reduction': round(off_ms / max(on_ms, 1e-9), 2),
            'radix_hits': on.radix_stats['hits'] - hits0,
            'radix_tokens_reused':
                on.radix_stats['tokens_reused'] - reused0,
        }
        sweep.append(row)
        print(f'overlap={overlap:.2f}: suffix {suffix:2d} tokens '
              f'(prefill bucket {sb:2d}/{L}), TTFT off '
              f'{off_ms:6.1f} ms vs on {on_ms:6.1f} ms '
              f'({row["ttft_reduction"]:.2f}x)', flush=True)

    out = {
        'description':
            'Automatic radix prefix caching: TTFT vs prefix-overlap '
            f'fraction on a tiny 2-layer llama (L={L} prompts, block '
            f'{bs}). With the tree warm, the radix engine matches the '
            'shared block-aligned prefix by refcount and prefills only '
            'the power-of-two-bucketed suffix, so prefill compute is '
            'proportional to (1 - overlap). CPU dryrun: '
            'direction-of-effect, not chip TTFT.',
        'prompt_len': L,
        'block_size': bs,
        'overlap_sweep': sweep,
        'radix_stats': dict(on.radix_stats),
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(out, f, indent=2)
        print(f'wrote {args.out}')


def kv_tier_report(args):
    """--kv-tier mode: host-RAM KV tier economics at working sets
    2-8x the device pool.  Analytic sweep at the target geometry plus
    a measured tiny-model sweep (CPU dryrun: direction-of-effect)."""
    import numpy as np

    from skypilot_tpu.infer.engine import resolve_cache_dtype
    from skypilot_tpu.models import get_model_config

    mc = get_model_config(args.model)
    dt = np.dtype(resolve_cache_dtype(args.cache_dtype))
    row_bytes = 2 * mc.num_kv_heads * mc.head_dim_ * dt.itemsize * \
        mc.num_layers
    bs = args.block_size
    kv_budget = int((args.hbm_gb - args.weights_gb) * (1 << 30))
    host_budget = int(args.host_kv_gb * (1 << 30))
    # A "typical prefix" a tenant re-references: --typical-len tokens,
    # block-rounded.  Restore moves its rows host->device; re-prefill
    # recomputes them (~2*params FLOPs/token at the target model).
    typical = args.typical_len
    blocks = -(-typical // bs)
    restore_bytes = blocks * bs * row_bytes
    restore_ms = restore_bytes / (args.h2d_gbps * 1e9) * 1e3
    params = args.weights_gb * (1 << 30)  # int8: ~1 byte/param
    reprefill_flops = 2 * params * typical
    reprefill_ms = reprefill_flops / (args.tflops * 1e12) * 1e3
    sweep = []
    for w in args.ws_sweep:
        working_set = w * kv_budget
        # Uniform re-reference over the hot set, LRU both tiers: each
        # tier covers its capacity fraction of the working set.
        device_hit = min(1.0, kv_budget / working_set)
        tier_hit = min(1.0, (kv_budget + host_budget) /
                       working_set) - device_hit
        miss = 1.0 - device_hit - tier_hit
        # Expected per-reference prefix cost, tier on vs off.  A
        # device hit costs ~0 (radix match), a tier hit costs the
        # restore (overlapped with the suffix prefill, so at worst the
        # transfer), a miss the full re-prefill.
        cost_off = (1.0 - device_hit) * reprefill_ms
        cost_on = tier_hit * restore_ms + miss * reprefill_ms
        row = {
            'ws_mult': w,
            'working_set_gb': round(working_set / (1 << 30), 1),
            'device_hit_rate': round(device_hit, 3),
            'host_hit_rate': round(tier_hit, 3),
            'miss_rate': round(max(miss, 0.0), 3),
            'restore_ms_per_prefix': round(restore_ms, 2),
            'reprefill_ms_per_prefix': round(reprefill_ms, 2),
            'restore_speedup': round(reprefill_ms / max(restore_ms,
                                                        1e-9), 2),
            'expected_prefix_cost_reduction':
                round(cost_off / max(cost_on, 1e-9), 2),
        }
        sweep.append(row)
        print(f'ws={w}x HBM ({row["working_set_gb"]:.1f} GB): device '
              f'hit {device_hit:.2f}, host-tier hit {tier_hit:.2f}, '
              f'miss {max(miss, 0.0):.2f} -> expected prefix cost '
              f'{row["expected_prefix_cost_reduction"]:.2f}x lower',
              flush=True)

    measured = None
    if not args.no_measure:
        measured = _measure_kv_tier_sweep(args)
    out = {
        'description':
            f'host-RAM KV tier at {args.model} geometry '
            f'(Hkv={mc.num_kv_heads}, D={mc.head_dim_}, '
            f'layers={mc.num_layers}, {dt.name} cache). Analytic: '
            'fraction of a uniform hot set covered by the device pool '
            f'vs a {args.host_kv_gb:.0f} GB host tier, and the cost '
            f'of restoring a {typical}-token prefix '
            f'({restore_bytes >> 10} KiB over {args.h2d_gbps:.0f} '
            'GB/s H2D, overlapped with the suffix prefill) vs '
            'recomputing it. measured_tiny_sweep cycles prefix '
            'families through a small device pool at 2-8x capacity '
            'and times round-2 re-references, tier on vs off (CPU '
            'dryrun: direction-of-effect, not chip TTFT).',
        'model': args.model,
        'block_size': bs,
        'kv_budget_bytes': kv_budget,
        'host_tier_budget_bytes': host_budget,
        'typical_prefix_tokens': typical,
        'working_set_sweep': sweep,
        'measured_tiny_sweep': measured,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(out, f, indent=2)
        print(f'wrote {args.out}')


def _measure_kv_tier_sweep(args):
    """Measured counterpart: a tiny 2-layer llama with a deliberately
    small paged pool (24 usable blocks) serving prefix families whose
    aggregate KV footprint is 2-8x that pool.  Round 1 seeds every
    family (evicting earlier ones; the tier-on engine spills victims
    to host RAM); round 2 re-references each family and times TTFT —
    tier-off re-prefills the full prompt monolithically, tier-on
    restores the spilled blocks and prefills only the suffix bucket."""
    import random as pyrandom

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig, InferenceEngine, Request
    from skypilot_tpu.models.llama import LlamaConfig

    bs = 8
    m = 256
    pool_blocks = 36           # usable; kv_blocks counts the dump too
    prefix_blocks = 31
    plen = prefix_blocks * bs  # 248 tokens; +1 probe token -> bucket 256
    cfg_m = LlamaConfig(name='kv-tier-micro', vocab_size=256,
                        hidden_size=128, intermediate_size=256,
                        num_layers=4, num_heads=4, num_kv_heads=2,
                        max_seq_len=m, tie_embeddings=True,
                        dtype='float32')
    common = dict(num_slots=2, max_cache_len=m, kv_block_size=bs,
                  kv_blocks=pool_blocks + 1,
                  prefill_buckets=(8, 32, 256), max_new_tokens=4,
                  cache_dtype=jnp.float32, auto_prefix_cache=True)
    off = InferenceEngine(cfg_m, InferConfig(**common))
    on = InferenceEngine(cfg_m, InferConfig(host_kv_bytes=32 << 20,
                                            **common),
                         params=off.params)
    # Deterministic warmup: the same helper the serve plane boots
    # with compiles every prefill/suffix bucket up front, so the
    # timed rounds see steady-state dispatches only.
    off.warmup()
    on.warmup()
    # The restore scatter itself is not in warmup()'s shape set: warm
    # it by seeding a throwaway family, churning it out of the pool,
    # and re-referencing it once on the tier-on engine.
    r = pyrandom.Random(1)
    warm = [r.randrange(1, 256) for _ in range(plen)]
    churn = [[r.randrange(1, 256) for _ in range(plen)]
             for _ in range(pool_blocks // prefix_blocks + 1)]
    for p in [warm] + churn + [warm]:
        on.generate([Request(tokens=list(p) + [1], max_new_tokens=1)])

    rows = []
    for w in args.ws_sweep:
        families = max(2, w * pool_blocks // prefix_blocks)
        prefixes = [[r.randrange(1, 256) for _ in range(plen)]
                    for _ in range(families)]
        row = {'ws_mult': w, 'families': families,
               'prefix_tokens': plen, 'prefix_blocks': prefix_blocks}
        for label, eng in (('tier_off', off), ('tier_on', on)):
            for p in prefixes:       # round 1: seed (and evict/spill)
                eng.generate([Request(tokens=list(p) + [1],
                                      max_new_tokens=1)])
            ht0 = eng.kv_health()['host_tier']
            hits0 = eng.radix_stats['hits']
            times = []
            for p in prefixes:       # round 2: hot re-reference
                t0 = time.time()
                eng.generate([Request(tokens=list(p) + [2],
                                      max_new_tokens=1)])
                times.append(time.time() - t0)
            times.sort()
            ht1 = eng.kv_health()['host_tier']
            row[f'ttft_ms_{label}'] = round(
                times[len(times) // 2] * 1e3, 2)
            row[f'radix_hits_{label}'] = \
                eng.radix_stats['hits'] - hits0
            if label == 'tier_on':
                restored = ht1['restores'] - ht0['restores']
                row['restored_blocks'] = restored
                row['restore_hit_rate'] = round(
                    min(1.0, restored /
                        max(families * prefix_blocks, 1)), 3)
        row['ttft_reduction'] = round(
            row['ttft_ms_tier_off'] /
            max(row['ttft_ms_tier_on'], 1e-9), 2)
        rows.append(row)
        print(f'measured ws={w}x ({families} families): TTFT off '
              f'{row["ttft_ms_tier_off"]:6.1f} ms vs on '
              f'{row["ttft_ms_tier_on"]:6.1f} ms '
              f'({row["ttft_reduction"]:.2f}x), restored '
              f'{row["restored_blocks"]} blocks (hit rate '
              f'{row["restore_hit_rate"]:.2f})', flush=True)
    return {
        'pool_blocks': pool_blocks,
        'host_tier_budget_bytes': 32 << 20,
        'rows': rows,
        'host_tier_final': dict(on.kv_health()['host_tier']),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', default='llama2-7b')
    ap.add_argument('--num-slots', type=int, default=16)
    ap.add_argument('--max-cache-len', type=int, default=512)
    ap.add_argument('--weight-dtype', default='int8')
    ap.add_argument('--cache-dtype', default='fp8')
    ap.add_argument('--prompt-len', type=int, default=219)
    ap.add_argument('--reps', type=int, default=20)
    ap.add_argument('--windows', type=int, nargs='+',
                    default=[1, 2, 4, 8, 16])
    ap.add_argument('--prefill-chunk', type=int, default=64,
                    help='chunk size for the worst-case decode-stall '
                         'comparison (0 skips it); must divide '
                         '--max-cache-len')
    ap.add_argument('--paged', action='store_true',
                    help='block-paged KV bandwidth/capacity report '
                         'instead of the dispatch-cost fit (CPU-safe)')
    ap.add_argument('--radix', action='store_true',
                    help='radix prefix-caching TTFT-vs-overlap sweep '
                         'instead of the dispatch-cost fit (CPU-safe)')
    ap.add_argument('--tp', action='store_true',
                    help='head-sharded paged pool vs tensor degree: '
                         'per-chip bandwidth + capacity model and a '
                         'measured tp=2 identity sweep (CPU-safe)')
    ap.add_argument('--tp-sweep', type=int, nargs='+',
                    default=[1, 2, 4, 8])
    ap.add_argument('--kv-tier', action='store_true',
                    help='host-RAM KV tier: hot-set coverage + '
                         'restore-vs-reprefill model at the target '
                         'geometry, and a measured tiny-model sweep '
                         'cycling prefix families at 2-8x the device '
                         'pool (CPU-safe)')
    ap.add_argument('--ws-sweep', type=int, nargs='+', default=[2, 4, 8],
                    help='working-set multiples of the device KV '
                         'budget for the --kv-tier sweep')
    ap.add_argument('--host-kv-gb', type=float, default=32.0,
                    help='host tier budget for the --kv-tier '
                         'analytic model')
    ap.add_argument('--h2d-gbps', type=float, default=8.0,
                    help='host->device transfer rate for the restore '
                         'cost model')
    ap.add_argument('--tflops', type=float, default=100.0,
                    help='sustained prefill TFLOP/s for the '
                         're-prefill cost model')
    ap.add_argument('--block-size', type=int, default=16)
    ap.add_argument('--fill-sweep', type=int, nargs='+',
                    default=[32, 64, 128, 256, 384])
    ap.add_argument('--typical-len', type=int, default=256,
                    help='steady-state resident rows/slot for the '
                         'capacity model (prompt + half the generation '
                         'budget at the serve-bench shape)')
    ap.add_argument('--hbm-gb', type=float, default=16.0,
                    help='HBM budget for the capacity model (v5e chip)')
    ap.add_argument('--weights-gb', type=float, default=7.0,
                    help='weight HBM at the target model (7B int8)')
    ap.add_argument('--no-measure', action='store_true',
                    help='skip the tiny-model measured sweep')
    ap.add_argument('--out', default=None,
                    help='write the --paged report JSON here')
    args = ap.parse_args()

    if args.paged:
        paged_report(args)
        return
    if args.radix:
        radix_report(args)
        return
    if args.kv_tier:
        kv_tier_report(args)
        return
    if args.tp:
        # The measured sweep needs >=2 devices; on the CPU dryrun that
        # means the virtual multi-device platform (no-op on real TPU
        # hosts or when the operator already set the flag).
        import os
        if os.environ.get('JAX_PLATFORMS', '') == 'cpu' and \
                '--xla_force_host_platform_device_count' not in \
                os.environ.get('XLA_FLAGS', ''):
            os.environ['XLA_FLAGS'] = (
                os.environ.get('XLA_FLAGS', '') +
                ' --xla_force_host_platform_device_count=8').strip()
        tp_report(args)
        return

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig, InferenceEngine
    from skypilot_tpu.infer.engine import resolve_cache_dtype
    from skypilot_tpu.models import get_model_config

    model_config = get_model_config(args.model)
    if args.weight_dtype == 'int8':
        model_config = dataclasses.replace(model_config,
                                           weight_dtype='int8')
    cfg = InferConfig(model=args.model, num_slots=args.num_slots,
                      max_cache_len=args.max_cache_len,
                      prefill_buckets=(256,),
                      cache_dtype=resolve_cache_dtype(args.cache_dtype),
                      decode_steps=max(args.windows))
    print(f'devices: {jax.devices()}', flush=True)
    t0 = time.time()
    eng = InferenceEngine(model_config, cfg)
    print(f'engine built in {time.time() - t0:.1f}s', flush=True)

    b = args.num_slots
    tokens = jnp.ones((b,), jnp.int32)
    lengths = jnp.full((b,), args.prompt_len, jnp.int32)
    temps = jnp.zeros((b,), jnp.float32)
    adapters = jnp.full((b,), -1, jnp.int32)
    key = jax.random.PRNGKey(0)
    cache = eng.cache

    def dispatch(cache, k):
        out = eng._decode(eng.params, cache, tokens, lengths, temps,
                          key, adapters, k)
        return out[0], out[3]     # packed head [K, B, 2+2k], new cache

    results = {}
    for k in args.windows:
        toks, cache = dispatch(cache, k)        # compile
        _ = float(toks[0, 0, 0])                   # sync (host transfer)
        # -- production shape: per-dispatch host transfer
        t0 = time.time()
        for _ in range(args.reps):
            toks, cache = dispatch(cache, k)
            _ = float(toks[0, 0, 0])
        prod = (time.time() - t0) / args.reps
        # -- pure device: chain dispatches, sync once at the end
        t0 = time.time()
        for _ in range(args.reps):
            toks, cache = dispatch(cache, k)
        _ = float(toks[0, 0, 0])
        pure = (time.time() - t0) / args.reps
        results[k] = {'dispatch_s': prod, 'chained_s': pure,
                      'tpot_ms': prod / k * 1e3,
                      'chained_per_step_ms': pure / k * 1e3}
        print(f'K={k:3d}: dispatch {prod * 1e3:7.1f} ms '
              f'(TPOT {prod / k * 1e3:6.1f} ms/tok) | chained '
              f'{pure * 1e3:7.1f} ms ({pure / k * 1e3:6.1f} ms/tok)',
              flush=True)

    # Linear fit over the production dispatch times: t(K) = F + K*s.
    ks = sorted(results)
    ts = [results[k]['dispatch_s'] for k in ks]
    n = len(ks)
    mk = sum(ks) / n
    mt = sum(ts) / n
    s = (sum((k - mk) * (t - mt) for k, t in zip(ks, ts)) /
         sum((k - mk) ** 2 for k in ks))
    f = mt - s * mk
    print(f'\nfit: dispatch(K) = {f * 1e3:.1f} ms + K * {s * 1e3:.1f} ms'
          f'  ->  TPOT(K) = {s * 1e3:.1f} + {f * 1e3:.1f}/K ms',
          flush=True)

    # Prefill component of TTFT at the bucket size.
    pre = jnp.ones((1, 256), jnp.int32)
    true_lens = jnp.asarray([args.prompt_len], jnp.int32)
    from skypilot_tpu.models.llama import init_cache
    slots = jnp.asarray([0], jnp.int32)
    pcache = init_cache(model_config, 1, 256, cfg.cache_dtype)
    out = eng._prefill_insert(eng.params, pre, true_lens, pcache,
                              cache, slots, temps[:1], key,
                              adapters[:1], False)
    _ = float(out[0][0, 0])
    # pcache is NOT donated (donate_argnums=(4,) is the engine cache):
    # reuse one allocation so the timed loop isolates the dispatch —
    # a per-rep init_cache would round-trip allocations on the tunnel
    # and overstate the prefill component.
    t0 = time.time()
    reps = max(5, args.reps // 2)
    for _ in range(reps):
        out = eng._prefill_insert(eng.params, pre, true_lens, pcache,
                                  out[2], slots, temps[:1], key,
                                  adapters[:1], False)
        _ = float(out[0][0, 0])
    prefill_ms = (time.time() - t0) / reps * 1e3
    print(f'prefill bucket=256 P=1: {prefill_ms:.1f} ms', flush=True)

    # Worst-case decode stall during a long-prompt prefill: monolithic
    # prefill stalls every active slot for the whole bucket dispatch;
    # chunked prefill (engine.py _chunk_round) stalls them for ONE
    # [B, C] chunk dispatch per gap — the stall-bound model of
    # docs/performance.md (TBT <= chunk_ms + window_ms).
    chunk_stall = None
    if args.prefill_chunk:
        c = args.prefill_chunk
        cache = out[2]        # the live cache (prior one was donated)
        # Monolithic stall at the PRODUCTION dispatch shape: _start_batch
        # always dispatches prefill_lanes wide (pad lanes duplicate the
        # last real row), so even a lone long-prompt arrival stalls
        # active slots for a [lanes, bucket] forward.
        lanes = cfg.prefill_lanes
        mtok = jnp.ones((lanes, 256), jnp.int32)
        mlens = jnp.full((lanes,), args.prompt_len, jnp.int32)
        mslots = jnp.zeros((lanes,), jnp.int32)
        mcache = init_cache(model_config, lanes, 256, cfg.cache_dtype)
        mtemps = jnp.zeros((lanes,), jnp.float32)
        maids = jnp.full((lanes,), -1, jnp.int32)
        out = eng._prefill_insert(eng.params, mtok, mlens, mcache, cache,
                                  mslots, mtemps, key, maids, False)
        _ = float(out[0][0, 0])                  # compile + sync
        t0 = time.time()
        for _ in range(reps):
            out = eng._prefill_insert(eng.params, mtok, mlens, mcache,
                                      out[2], mslots, mtemps, key,
                                      maids, False)
            _ = float(out[0][0, 0])
        mono_ms = (time.time() - t0) / reps * 1e3
        # Chunked stall: ONE full-width [B, C] chunk dispatch
        # (_chunk_round advances every chunking slot per serving gap).
        ctokens = jnp.ones((b, c), jnp.int32)
        cstarts = jnp.zeros((b,), jnp.int32)
        ctrue = jnp.full((b,), c - 1, jnp.int32)
        out = eng._chunk_prefill(eng.params, ctokens, cstarts, ctrue,
                                 out[2], temps, key, adapters)
        _ = float(out[0][0, 0])                  # compile + sync
        t0 = time.time()
        for _ in range(reps):
            out = eng._chunk_prefill(eng.params, ctokens, cstarts,
                                     ctrue, out[1], temps, key,
                                     adapters)
            _ = float(out[0][0, 0])
        chunk_ms = (time.time() - t0) / reps * 1e3
        chunk_stall = {
            'prefill_chunk': c,
            'prefill_lanes': lanes,
            'worst_case_stall_ms_monolithic': round(mono_ms, 2),
            'worst_case_stall_ms_chunked': round(chunk_ms, 2),
            'stall_reduction': round(mono_ms / chunk_ms, 2),
        }
        print(f'worst-case decode stall: monolithic [{lanes}, 256] '
              f'{mono_ms:.1f} ms vs one [{b}, {c}] chunk {chunk_ms:.1f} '
              f'ms ({mono_ms / chunk_ms:.1f}x)', flush=True)

    print(json.dumps({'model': args.model, 'num_slots': b,
                      'max_cache_len': args.max_cache_len,
                      'windows': {str(k): results[k] for k in results},
                      'fit_fixed_ms': f * 1e3,
                      'fit_per_step_ms': s * 1e3,
                      'prefill_bucket256_p1_ms': round(prefill_ms, 2),
                      'chunk_stall': chunk_stall}))


if __name__ == '__main__':
    main()
