#!/usr/bin/env python3
"""Decode-dispatch microbenchmark: where does TPOT actually go?

Separates, on the real chip, the three components of the serving
engine's inter-token latency (VERDICT r3 weak #2: TPOT p50 ~80-100 ms
through the plane vs the reference anchor's 18.9 ms on 8x v6e):

  1. pure device time per decode step  — chain M dispatches, sync once;
  2. production dispatch time          — per-dispatch host transfer of
     the [K, B] token block, exactly what _decode_step does;
  3. prefill dispatch time per bucket  — the TTFT device component.

(2) - (1) is the host<->device round-trip tax (on a tunneled chip this
is the dominant suspect).  Fitting time(K) = F + K*s over K in
{1,2,4,8,16} gives the fixed-overhead F and marginal per-step cost s:
TPOT at window K is (F + K*s)/K = s + F/K, which says exactly how much
window amortization the tunnel forces.

Usage (on the TPU host):
  python scripts/bench_decode_micro.py [--model llama2-7b]
      [--num-slots 16] [--max-cache-len 512] [--reps 20]
"""
import argparse
import dataclasses
import json
import sys
import time

sys.path.insert(0, '.')


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', default='llama2-7b')
    ap.add_argument('--num-slots', type=int, default=16)
    ap.add_argument('--max-cache-len', type=int, default=512)
    ap.add_argument('--weight-dtype', default='int8')
    ap.add_argument('--cache-dtype', default='fp8')
    ap.add_argument('--prompt-len', type=int, default=219)
    ap.add_argument('--reps', type=int, default=20)
    ap.add_argument('--windows', type=int, nargs='+',
                    default=[1, 2, 4, 8, 16])
    ap.add_argument('--prefill-chunk', type=int, default=64,
                    help='chunk size for the worst-case decode-stall '
                         'comparison (0 skips it); must divide '
                         '--max-cache-len')
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig, InferenceEngine
    from skypilot_tpu.infer.engine import resolve_cache_dtype
    from skypilot_tpu.models import get_model_config

    model_config = get_model_config(args.model)
    if args.weight_dtype == 'int8':
        model_config = dataclasses.replace(model_config,
                                           weight_dtype='int8')
    cfg = InferConfig(model=args.model, num_slots=args.num_slots,
                      max_cache_len=args.max_cache_len,
                      prefill_buckets=(256,),
                      cache_dtype=resolve_cache_dtype(args.cache_dtype),
                      decode_steps=max(args.windows))
    print(f'devices: {jax.devices()}', flush=True)
    t0 = time.time()
    eng = InferenceEngine(model_config, cfg)
    print(f'engine built in {time.time() - t0:.1f}s', flush=True)

    b = args.num_slots
    tokens = jnp.ones((b,), jnp.int32)
    lengths = jnp.full((b,), args.prompt_len, jnp.int32)
    temps = jnp.zeros((b,), jnp.float32)
    adapters = jnp.full((b,), -1, jnp.int32)
    key = jax.random.PRNGKey(0)
    cache = eng.cache

    def dispatch(cache, k):
        out = eng._decode(eng.params, cache, tokens, lengths, temps,
                          key, adapters, k)
        return out[0], out[3]     # packed head [K, B, 2+2k], new cache

    results = {}
    for k in args.windows:
        toks, cache = dispatch(cache, k)        # compile
        _ = float(toks[0, 0, 0])                   # sync (host transfer)
        # -- production shape: per-dispatch host transfer
        t0 = time.time()
        for _ in range(args.reps):
            toks, cache = dispatch(cache, k)
            _ = float(toks[0, 0, 0])
        prod = (time.time() - t0) / args.reps
        # -- pure device: chain dispatches, sync once at the end
        t0 = time.time()
        for _ in range(args.reps):
            toks, cache = dispatch(cache, k)
        _ = float(toks[0, 0, 0])
        pure = (time.time() - t0) / args.reps
        results[k] = {'dispatch_s': prod, 'chained_s': pure,
                      'tpot_ms': prod / k * 1e3,
                      'chained_per_step_ms': pure / k * 1e3}
        print(f'K={k:3d}: dispatch {prod * 1e3:7.1f} ms '
              f'(TPOT {prod / k * 1e3:6.1f} ms/tok) | chained '
              f'{pure * 1e3:7.1f} ms ({pure / k * 1e3:6.1f} ms/tok)',
              flush=True)

    # Linear fit over the production dispatch times: t(K) = F + K*s.
    ks = sorted(results)
    ts = [results[k]['dispatch_s'] for k in ks]
    n = len(ks)
    mk = sum(ks) / n
    mt = sum(ts) / n
    s = (sum((k - mk) * (t - mt) for k, t in zip(ks, ts)) /
         sum((k - mk) ** 2 for k in ks))
    f = mt - s * mk
    print(f'\nfit: dispatch(K) = {f * 1e3:.1f} ms + K * {s * 1e3:.1f} ms'
          f'  ->  TPOT(K) = {s * 1e3:.1f} + {f * 1e3:.1f}/K ms',
          flush=True)

    # Prefill component of TTFT at the bucket size.
    pre = jnp.ones((1, 256), jnp.int32)
    true_lens = jnp.asarray([args.prompt_len], jnp.int32)
    from skypilot_tpu.models.llama import init_cache
    slots = jnp.asarray([0], jnp.int32)
    pcache = init_cache(model_config, 1, 256, cfg.cache_dtype)
    out = eng._prefill_insert(eng.params, pre, true_lens, pcache,
                              cache, slots, temps[:1], key,
                              adapters[:1], False)
    _ = float(out[0][0, 0])
    # pcache is NOT donated (donate_argnums=(4,) is the engine cache):
    # reuse one allocation so the timed loop isolates the dispatch —
    # a per-rep init_cache would round-trip allocations on the tunnel
    # and overstate the prefill component.
    t0 = time.time()
    reps = max(5, args.reps // 2)
    for _ in range(reps):
        out = eng._prefill_insert(eng.params, pre, true_lens, pcache,
                                  out[2], slots, temps[:1], key,
                                  adapters[:1], False)
        _ = float(out[0][0, 0])
    prefill_ms = (time.time() - t0) / reps * 1e3
    print(f'prefill bucket=256 P=1: {prefill_ms:.1f} ms', flush=True)

    # Worst-case decode stall during a long-prompt prefill: monolithic
    # prefill stalls every active slot for the whole bucket dispatch;
    # chunked prefill (engine.py _chunk_round) stalls them for ONE
    # [B, C] chunk dispatch per gap — the stall-bound model of
    # docs/performance.md (TBT <= chunk_ms + window_ms).
    chunk_stall = None
    if args.prefill_chunk:
        c = args.prefill_chunk
        cache = out[2]        # the live cache (prior one was donated)
        # Monolithic stall at the PRODUCTION dispatch shape: _start_batch
        # always dispatches prefill_lanes wide (pad lanes duplicate the
        # last real row), so even a lone long-prompt arrival stalls
        # active slots for a [lanes, bucket] forward.
        lanes = cfg.prefill_lanes
        mtok = jnp.ones((lanes, 256), jnp.int32)
        mlens = jnp.full((lanes,), args.prompt_len, jnp.int32)
        mslots = jnp.zeros((lanes,), jnp.int32)
        mcache = init_cache(model_config, lanes, 256, cfg.cache_dtype)
        mtemps = jnp.zeros((lanes,), jnp.float32)
        maids = jnp.full((lanes,), -1, jnp.int32)
        out = eng._prefill_insert(eng.params, mtok, mlens, mcache, cache,
                                  mslots, mtemps, key, maids, False)
        _ = float(out[0][0, 0])                  # compile + sync
        t0 = time.time()
        for _ in range(reps):
            out = eng._prefill_insert(eng.params, mtok, mlens, mcache,
                                      out[2], mslots, mtemps, key,
                                      maids, False)
            _ = float(out[0][0, 0])
        mono_ms = (time.time() - t0) / reps * 1e3
        # Chunked stall: ONE full-width [B, C] chunk dispatch
        # (_chunk_round advances every chunking slot per serving gap).
        ctokens = jnp.ones((b, c), jnp.int32)
        cstarts = jnp.zeros((b,), jnp.int32)
        ctrue = jnp.full((b,), c - 1, jnp.int32)
        out = eng._chunk_prefill(eng.params, ctokens, cstarts, ctrue,
                                 out[2], temps, key, adapters)
        _ = float(out[0][0, 0])                  # compile + sync
        t0 = time.time()
        for _ in range(reps):
            out = eng._chunk_prefill(eng.params, ctokens, cstarts,
                                     ctrue, out[1], temps, key,
                                     adapters)
            _ = float(out[0][0, 0])
        chunk_ms = (time.time() - t0) / reps * 1e3
        chunk_stall = {
            'prefill_chunk': c,
            'prefill_lanes': lanes,
            'worst_case_stall_ms_monolithic': round(mono_ms, 2),
            'worst_case_stall_ms_chunked': round(chunk_ms, 2),
            'stall_reduction': round(mono_ms / chunk_ms, 2),
        }
        print(f'worst-case decode stall: monolithic [{lanes}, 256] '
              f'{mono_ms:.1f} ms vs one [{b}, {c}] chunk {chunk_ms:.1f} '
              f'ms ({mono_ms / chunk_ms:.1f}x)', flush=True)

    print(json.dumps({'model': args.model, 'num_slots': b,
                      'max_cache_len': args.max_cache_len,
                      'windows': {str(k): results[k] for k in results},
                      'fit_fixed_ms': f * 1e3,
                      'fit_per_step_ms': s * 1e3,
                      'prefill_bucket256_p1_ms': round(prefill_ms, 2),
                      'chunk_stall': chunk_stall}))


if __name__ == '__main__':
    main()
