#!/usr/bin/env python3
"""Speculative decoding measured where it can pay (r3 verdict #2).

The r3 feature benchmark ran prompt-lookup drafting against RANDOM-init
weights and random prompts: the verify path never engaged (dispatches=0)
and the recorded delta was run variance.  The fix is a model whose
greedy continuation actually AGREES with prompt-lookup drafts on
grounded traffic — i.e. a model that copies from its context.

This script:

1. **Trains** a ~160M llama on-chip on induction data — tiled
   `[segment ; segment ; ...]` rows (random tokens repeated, length
   log-uniform), the minimal task that teaches copy-from-context.
   (llama-800m was tried first and never left the unigram floor in an
   on-chip budget; the 160M learns partial induction by the 6,000-step
   default ≈ 98M tokens.)
2. **Benchmarks** the engine on grounded traffic: each prompt is a
   fresh `doc + start-of-repeat`; to the degree the model copies,
   prompt-lookup drafts the same copy and the verify dispatch accepts —
   measuring the REAL acceptance rate, copy fidelity, and throughput
   delta of `draft_len` 4 and 7 vs the windowed decode (`draft_len=0`).
3. Also records an **ungrounded** row (random continuation traffic) —
   the EMA-gate no-regression half of the story.

Measured r4 outcome (BENCH_FEATURES_r04.json, docs/performance.md):
acceptance 8-12%, far below the 7B dispatch-cost break-even (~83%) —
speculation stays parked (default draft_len=0).

Parity: vLLM's prompt-lookup speculator (the reference consumes it via
recipes); JetStream has no speculative path.

Usage:  python scripts/bench_speculative.py --out spec_r04.json
"""
import argparse
import gc
import json
import sys
import time

sys.path.insert(0, '.')

MODEL = 'llama-induct-160m'
SEG = 256                     # training: [seg;seg;...] tiled rows
DOC = 32                      # eval: doc length to copy from
CUE = 8                       # eval: repeated prefix cueing the copy
NEW = 24                      # eval: tokens to generate (the copy)

# In-script config: a ~160M llama.  Small models form induction heads
# within tens of millions of tokens (the 800m at 1500 steps x 8k
# tokens never left the unigram floor — the phase change needs more
# tokens the bigger the model); 160M learns the pure-copy task fast
# and the ACCEPTANCE RATE it yields transfers: drafting is a property
# of the traffic + the model's copying fidelity, and the 7B throughput
# implication comes from the measured dispatch-cost break-even table
# (bench_features.py), not from this model's absolute tok/s.
_CUSTOM = {
    'llama-induct-160m': dict(vocab_size=32000, hidden_size=768,
                              intermediate_size=2048, num_layers=8,
                              num_heads=12, num_kv_heads=12,
                              max_seq_len=1024, tie_embeddings=True),
}


def model_config(name: str):
    if name in _CUSTOM:
        from skypilot_tpu.models.llama import LlamaConfig
        return LlamaConfig(name=name, **_CUSTOM[name])
    from skypilot_tpu.models import get_model_config
    return get_model_config(name)


def induction_batches(batch_size, vocab_size, seed=0):
    """[seg ; seg ; ...] rows with a VARIED segment length per batch:
    a fixed length teaches position-based copying (offset -SEG), which
    fails the moment the eval offset differs — varying it forces
    content-based induction (match the n-gram, copy what followed)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    seq = 2 * SEG + 1        # trainer convention: seq_len + 1 columns
    while True:
        # Log-uniform lengths: short segments (close matches) carry the
        # early copy signal — induction emerges bottom-up.  Within this
        # script's on-chip budget (~100M tokens) the model masters
        # short/medium segments; the eval DOC sits inside that regime.
        length = int(np.exp(rng.uniform(np.log(8), np.log(SEG))))
        seg = rng.integers(1, vocab_size, size=(batch_size, length),
                           dtype=np.int32)
        reps = -(-seq // length)          # ceil: tile then crop
        yield {'tokens': np.tile(seg, (1, reps))[:, :seq]}


def train(steps: int):
    """Train the induction task; returns (bf16 param tree, last losses)."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.parallel import MeshSpec, make_mesh
    from skypilot_tpu.train import TrainConfig, create_sharded_state
    from skypilot_tpu.train.trainer import make_train_step

    cfg = model_config(MODEL)
    batch = 32
    tcfg = TrainConfig(model=MODEL, batch_size=batch, seq_len=2 * SEG,
                       learning_rate=6e-4, warmup_steps=100,
                       total_steps=steps)
    mesh = make_mesh(MeshSpec.auto(len(jax.devices())))
    state, _ = create_sharded_state(cfg, tcfg, mesh, jax.random.PRNGKey(0))
    step_fn = make_train_step(mesh, loss_chunk=128)
    data = induction_batches(batch, cfg.vocab_size)
    losses = []
    t0 = time.time()
    with mesh:
        for i in range(steps):
            state, metrics = step_fn(state, next(data))
            if i % 50 == 0 or i == steps - 1:
                loss = float(metrics['loss'])   # host sync
                losses.append(round(loss, 3))
                print(f'step {i}: loss {loss:.3f} '
                      f'({time.time() - t0:.0f}s)', flush=True)
    # bf16 for serving, ON DEVICE (a host copy would re-upload per
    # dispatch); dropping the TrainState frees the f32 + Adam HBM.
    params = jax.tree.map(lambda x: jnp.asarray(x, jnp.bfloat16),
                          state.params)
    jax.block_until_ready(params)
    del state, step_fn
    gc.collect()
    return params, losses


def grounded_requests(n, vocab_size, seed=1):
    """Fresh doc per request + CUE-token repeat cue; the trained model
    copies doc[CUE:], which is exactly what prompt-lookup drafts."""
    import numpy as np

    from skypilot_tpu.infer import Request
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n):
        doc = rng.integers(1, vocab_size, size=DOC).tolist()
        reqs.append(Request(tokens=doc + doc[:CUE], max_new_tokens=NEW))
    return reqs


def random_requests(n, vocab_size, seed=2):
    import numpy as np

    from skypilot_tpu.infer import Request
    rng = np.random.default_rng(seed)
    return [
        Request(tokens=rng.integers(1, vocab_size,
                                    size=DOC + CUE).tolist(),
                max_new_tokens=NEW) for _ in range(n)
    ]


def run_engine(params, draft_len, reqs, label, out, copy_check=None):
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig, InferenceEngine, Request
    cfg = InferConfig(model=MODEL, num_slots=16, max_cache_len=256,
                      prefill_buckets=(64, 136, 256), decode_steps=8,
                      cache_dtype=jnp.bfloat16, draft_len=draft_len)
    eng = InferenceEngine(model_config(MODEL), cfg,
                          params={'params': params})
    # Warm both compile paths outside the measurement.
    eng.generate([Request(tokens=list(reqs[0].tokens), max_new_tokens=2)])
    eng._warm_spec(len(reqs[0].tokens))
    for k in eng.spec_stats:
        eng.spec_stats[k] = 0
    t0 = time.time()
    results = eng.generate([
        Request(tokens=list(r.tokens), max_new_tokens=r.max_new_tokens)
        for r in reqs
    ])
    elapsed = time.time() - t0
    st = dict(eng.spec_stats)
    row = {
        'output_tokens_per_second': round(
            sum(len(r.output_tokens) for r in results) / elapsed, 1),
        'requests_per_second': round(len(results) / elapsed, 2),
        'spec': st,
    }
    if st['drafted']:
        row['accept_rate'] = round(st['accepted'] / st['drafted'], 3)
    if st['dispatches']:
        row['tokens_per_dispatch'] = round(
            1 + st['accepted'] / st['dispatches'], 2)
    if copy_check is not None:
        # Fidelity: fraction of generated tokens equal to the copy the
        # doc dictates (the model must have LEARNED the task, or the
        # whole measurement is vacuous).
        good = total = 0
        for req, res in zip(reqs, results):
            want = (req.tokens[:DOC] * 2)[DOC + CUE:DOC + CUE +
                                          len(res.output_tokens)]
            good += sum(int(a == b)
                        for a, b in zip(res.output_tokens, want))
            total += len(res.output_tokens)
        row['copy_fidelity'] = round(good / max(total, 1), 3)
    out[label] = row
    del eng
    gc.collect()
    return row


def main():
    global MODEL, SEG, DOC, NEW
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=6000)
    ap.add_argument('--requests', type=int, default=32)
    ap.add_argument('--model', default=MODEL,
                    help='registry model (llama-debug for CPU smoke)')
    ap.add_argument('--platform', default=None,
                    choices=['cpu', 'tpu'],
                    help='pin jax (config.update AFTER import — site '
                         'hooks rewrite JAX_PLATFORMS)')
    ap.add_argument('--out', default=None)
    args = ap.parse_args()
    MODEL = args.model
    if args.platform:
        import jax
        jax.config.update('jax_platforms', args.platform)

    mcfg = model_config(MODEL)
    vocab = mcfg.vocab_size
    if mcfg.max_seq_len < 2 * SEG:   # CPU smoke with llama-debug
        SEG = mcfg.max_seq_len // 2
        DOC = SEG // 2
        NEW = DOC - CUE

    params, losses = train(args.steps)
    out = {
        'description':
            f'speculative decoding on a TRAINED {MODEL} (induction task:'
            ' [seg;seg] copy, trained on-chip), bf16 serving. grounded ='
            f' fresh doc({DOC}) + {CUE}-token repeat cue, {NEW} new'
            ' tokens (the model copies; prompt-lookup drafts the same'
            ' copy). ungrounded = random prompts (acceptance ~0; the EMA'
            ' gate falls back to windowed decode).',
        'train_loss_trajectory': losses,
        'train_steps': args.steps,
    }
    grounded = grounded_requests(args.requests, vocab)
    rnd = random_requests(args.requests, vocab)
    run_engine(params, 0, grounded, 'grounded_draft_0', out,
               copy_check=True)
    print(json.dumps(out['grounded_draft_0']), flush=True)
    run_engine(params, 4, grounded, 'grounded_draft_4', out,
               copy_check=True)
    print(json.dumps(out['grounded_draft_4']), flush=True)
    run_engine(params, 7, grounded, 'grounded_draft_7', out,
               copy_check=True)
    print(json.dumps(out['grounded_draft_7']), flush=True)
    run_engine(params, 4, rnd, 'ungrounded_draft_4', out)
    print(json.dumps(out['ungrounded_draft_4']), flush=True)
    d0 = out['grounded_draft_0']['output_tokens_per_second']
    d4 = out['grounded_draft_4']['output_tokens_per_second']
    d7 = out['grounded_draft_7']['output_tokens_per_second']
    out['grounded_speedup_draft_4'] = round(d4 / d0, 3)
    out['grounded_speedup_draft_7'] = round(d7 / d0, 3)
    print(json.dumps({k: v for k, v in out.items()
                      if k.startswith('grounded_speedup')}))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(out, f, indent=2)
        print(f'wrote {args.out}')


if __name__ == '__main__':
    main()
