#!/usr/bin/env python
"""Chaos smoke: seeded fault sweep over the small model.

Tier-1 companion to tests/test_faults.py: where the tests pin exact
scenarios (one fault, one assertion), this sweep arms a *mixture* of
probabilistic faults across every injection site and checks the two
properties that must hold under ANY fault sequence:

  1. **No hang** — every serving episode drains within its wall bound
     (nothing waits on a dead loop or a stuck allocator).
  2. **Full request accounting** — every submitted request gets exactly
     one terminal result (ok / error / deadline), and the paged block
     pool balances at drain (all blocks free, refcounts zero).

Probabilistic specs draw from per-spec seeded streams (FaultPlan), so
a failing seed reproduces exactly:  scripts/chaos_smoke.py --seeds 3

``--multi-replica N`` switches to the replica-plane sweep: N killable
in-process replicas behind the real load balancer, with a seeded
killer thread consulting the plan's ``replica_kill`` site and
preempting replicas mid-decode.  The property there is the tentpole
one: **every greedy request completes byte-identical to the fault-free
run** — zero failed requests under replica preemption — plus a drain
exercise asserting a draining replica finishes its in-flight stream
while the LB answers zero 5xx.

The multi-replica sweep ends with two control-plane legs (PR 18):

  3. **LB kill + warm restart** — the load balancer itself is killed
     mid-traffic and restarted on the same port with its journal
     re-adopted; clients retry connection errors, and every request
     must still land byte-identical (zero lost through the outage).
  4. **Gray-failure probation** — one replica is wrapped in a seeded
     latency-injection proxy (``net_degrade`` site); the LB's TTFT
     outlier track must put it in probation within the detection
     window while traffic through it stays byte-identical.

``--batch`` runs the durable-batch leg (PR 20) instead: one journaled
batch job submitted through a BatchCoordinator, with a replica, the
LB, and the coordinator itself each killed mid-job.  The resumed
job's final output file must be byte-identical to the fault-free
run's — zero lost rows, zero duplicated spool writes (exactly-once),
zero determinism violations — and the restarted LB must show it
re-adopted the orphaned row leases from its journal.

Exit code: 0 = all episodes passed, 1 = any property violated.
"""
import argparse
import copy
import functools
import json
import os
import queue
import sys
import threading
import time
from http.client import HTTPConnection

sys.path.insert(0, '.')

# On the CPU dryrun, give the process a virtual multi-device platform
# BEFORE jax loads so the multi-replica sweep can include a tp=2
# replica (no-op on real TPU hosts or when the operator set the flag).
if os.environ.get('JAX_PLATFORMS', '') == 'cpu' and \
        '--xla_force_host_platform_device_count' not in \
        os.environ.get('XLA_FLAGS', ''):
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        ' --xla_force_host_platform_device_count=8').strip()

import jax
import jax.numpy as jnp

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.infer import (FaultPlan, FaultSpec, InferConfig,
                                InferenceEngine, Request)
from skypilot_tpu.models.llama import LlamaConfig

EPISODE_WALL_S = 120.0


def build_engine() -> InferenceEngine:
    mc = LlamaConfig(name='chaos-smoke', vocab_size=101, hidden_size=32,
                     intermediate_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=128,
                     tie_embeddings=True, dtype='float32')
    cfg = InferConfig(num_slots=4, max_cache_len=64,
                      prefill_buckets=(8, 16, 32), max_new_tokens=8,
                      cache_dtype=jnp.float32, kv_block_size=8)
    return InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0))


def make_plan(seed: int) -> FaultPlan:
    """A bit of everything: attributed and unattributed dispatch
    faults, allocator pressure, NaN lanes, stalls, and loop death."""
    return FaultPlan(seed=seed, specs=[
        FaultSpec(site='decode_step', prob=0.10, slot=1, max_fires=2),
        FaultSpec(site='decode_step', prob=0.04, max_fires=1),
        FaultSpec(site='prefill', prob=0.10, max_fires=2),
        FaultSpec(site='chunk_round', prob=0.10, max_fires=1),
        FaultSpec(site='block_alloc', prob=0.15, max_fires=4),
        FaultSpec(site='nonfinite_logits', prob=0.08, slot=0,
                  max_fires=2),
        FaultSpec(site='stall', prob=0.10, stall_s=0.05),
        FaultSpec(site='serve_loop', prob=0.05, max_fires=2),
    ])


def make_requests(n: int):
    reqs = []
    for i in range(n):
        toks = [(5 * i + j) % 97 + 1 for j in range(3 + i % 5)]
        reqs.append(Request(
            request_id=f'r{i}', tokens=toks,
            max_new_tokens=4 + i % 12,
            # Every 5th request carries a (generous) deadline so the
            # eviction path runs inside the sweep too.
            deadline_s=30.0 if i % 5 == 0 else None))
    return reqs


def episode(eng: InferenceEngine, seed: int, n: int) -> list:
    """One serving episode under an armed plan; returns violations."""
    plan = make_plan(seed)
    reqs = make_requests(n)
    results, q, stop = {}, queue.Queue(), threading.Event()
    for r in reqs:
        q.put(copy.deepcopy(r))
    eng.arm_faults(plan)
    loop_exc = []

    def run():
        try:
            eng.generate_stream(
                q, lambda res: results.setdefault(res.request_id, res),
                stop)
        except Exception as e:  # supervisor gave up: legal iff every
            loop_exc.append(e)  # request was still accounted for
    t = threading.Thread(target=run, daemon=True)
    t0 = time.time()
    t.start()
    try:
        while len(results) < n and time.time() - t0 < EPISODE_WALL_S:
            if loop_exc and len(results) >= n:
                break
            time.sleep(0.02)
    finally:
        stop.set()
        t.join(timeout=30)
        eng.disarm_faults()

    bad = []
    if t.is_alive():
        bad.append('HANG: serving loop did not stop')
    if len(results) != n:
        missing = sorted(set(r.request_id for r in reqs) - set(results))
        bad.append(f'ACCOUNTING: {len(results)}/{n} results; '
                   f'missing {missing}')
    reasons = {}
    for res in results.values():
        reasons[res.finish_reason] = reasons.get(res.finish_reason,
                                                 0) + 1
        if res.finish_reason not in ('length', 'eos', 'error',
                                     'deadline'):
            bad.append(f'BAD finish_reason {res.finish_reason!r} '
                       f'for {res.request_id}')
    if eng._paged:
        # Full conservation law (refcounts == slot tables + radix +
        # prefixes, free list == zero-ref blocks), then the stricter
        # drained-pool expectation: nothing in flight may hold blocks.
        try:
            sanitizers.check_block_conservation(eng)
        except sanitizers.BlockLeakError as e:
            bad.append(f'BLOCK LEAK: {e}')
    if sanitizers.compile_sanitizer_enabled():
        # Fault storms must not smuggle unbucketed shapes into the jit
        # roots: measured compiles stay within the provable bound.
        try:
            sanitizers.check_compile_budget(eng)
        except sanitizers.CompileBudgetError as e:
            bad.append(f'COMPILE STORM: {e}')
        held = eng._num_blocks - 1 - len(eng._free_blocks)
        radix_held = eng._radix.blocks_held if eng._radix else 0
        prefix_held = sum(len(e.get('blocks', ()))
                          for e in eng._prefixes.values())
        if held != radix_held + prefix_held:
            bad.append(
                f'BLOCK LEAK: {held} blocks held at drain but only '
                f'{radix_held} radix + {prefix_held} prefix expected; '
                f'refs={eng._block_refs.tolist()}')
    if sanitizers.shard_sanitizer_enabled():
        # Fault storms must not re-commit root inputs off their
        # declared layouts (no-op for mesh-less engines).
        try:
            sanitizers.check_shard_layout(eng)
        except sanitizers.ShardLayoutError as e:
            bad.append(f'SHARD DRIFT: {e}')
    print(f'  seed={seed}: {reasons} wall={time.time() - t0:.1f}s '
          f'fired={plan.stats()["fired"]} '
          f'counters={eng.fault_stats} '
          f'{"terminal-giveup " if loop_exc else ""}'
          f'{"FAIL" if bad else "ok"}')
    return bad


# ------------------------------------------------ multi-replica sweep


def _replica_engine(tp: int = 0, stall_s: float = 0.04) -> InferenceEngine:
    from skypilot_tpu.parallel import tp_mesh
    mc = LlamaConfig(name='chaos-replica', vocab_size=101,
                     hidden_size=32, intermediate_size=64, num_layers=2,
                     num_heads=4, num_kv_heads=2, max_seq_len=128,
                     tie_embeddings=True, dtype='float32')
    cfg = InferConfig(num_slots=4, max_cache_len=64,
                      prefill_buckets=(8, 16, 32), max_new_tokens=32,
                      cache_dtype=jnp.float32, decode_steps=4,
                      kv_block_size=8, auto_prefix_cache=True,
                      host_kv_bytes=32 << 20)
    eng = InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0),
                          mesh=tp_mesh(tp))
    # Stretch generations across loop iterations so kills land while
    # streams are genuinely in flight (sleep only; tokens unaffected).
    eng.arm_faults(FaultPlan(seed=0, specs=[
        FaultSpec(site='stall', prob=1.0, stall_s=stall_s)]))
    return eng


def _request_spec(i: int) -> dict:
    return {'tokens': [(3 * i + j) % 97 + 1 for j in range(4 + i % 4)],
            'max_new_tokens': 12 + i % 5, 'stream': True}


def _stream_generate(port: int, payload: dict, timeout: float = 60.0):
    """POST /generate via the LB; returns the parsed SSE event list."""
    conn = HTTPConnection('127.0.0.1', port, timeout=timeout)
    try:
        conn.request('POST', '/generate',
                     body=json.dumps(payload).encode(),
                     headers={'Content-Type': 'application/json'})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f'LB answered {resp.status}')
        buf, events = b'', []
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            while b'\n\n' in buf:
                ev, buf = buf.split(b'\n\n', 1)
                for line in ev.split(b'\n'):
                    if line.startswith(b'data: '):
                        events.append(json.loads(line[6:]))
        return events
    finally:
        conn.close()


def _finish_of(events):
    done = [e for e in events if e.get('done')]
    if len(done) != 1:
        raise RuntimeError(f'{len(done)} terminal events')
    return done[0]


def _drain_exercise(fleet, references) -> list:
    """Drain the replica serving an in-flight stream: the stream must
    complete (byte-identical) and the LB must answer zero 5xx."""
    bad = []
    result, exc = {}, []

    def client():
        try:
            result['events'] = _stream_generate(
                fleet.lb.port, _request_spec(0))
        except Exception as e:  # noqa: BLE001
            exc.append(e)

    t = threading.Thread(target=client, daemon=True)
    t.start()
    deadline = time.time() + 30
    busy = None
    while time.time() < deadline and busy is None:
        busy = next((r for r in fleet.replicas if r.busy()), None)
        time.sleep(0.01)
    if busy is None:
        return ['DRAIN: stream never reached a replica']
    # Seed a hot radix prefix on the soon-to-drain replica: the LB
    # must ship it to a survivor (warm failover) once it observes the
    # drain, and the adopter must answer the matching prompt off the
    # adopted blocks — byte-identical, suffix-only prefill.
    hot = [7] * 24   # three full blocks at kv_block_size=8
    hot_ref = None
    try:
        hot_ref = _finish_of(_stream_generate(
            busy.port, {'tokens': hot + [90], 'max_new_tokens': 3,
                        'stream': True}))['output_tokens']
    except RuntimeError as e:
        bad.append(f'DRAIN: hot seed request failed: {e}')
    conn = HTTPConnection('127.0.0.1', busy.port, timeout=10)
    conn.request('POST', '/drain', body=b'{"deadline_s": 60}')
    if conn.getresponse().status != 200:
        bad.append('DRAIN: /drain rejected')
    conn.close()
    for i in range(1, 5):
        try:
            done = _finish_of(_stream_generate(fleet.lb.port,
                                               _request_spec(i)))
            if done.get('output_tokens') != references[i]:
                bad.append(f'DRAIN: request {i} diverged')
        except RuntimeError as e:
            bad.append(f'DRAIN: request {i} during drain: {e}')
    t.join(60)
    if t.is_alive() or exc:
        bad.append(f'DRAIN: in-flight stream failed: {exc}')
    elif _finish_of(result['events']).get('output_tokens') != \
            references[0]:
        bad.append('DRAIN: in-flight stream diverged')
    if not busy.server.drained.wait(30):
        bad.append('DRAIN: replica never reported drained')
    # Warm failover: the drained replica's hot set ships to the
    # affinity-ring owner of EACH prefix, so with several survivors
    # the prefixes can split across them (ring order follows the
    # randomized ports).  Wait for the handoff to finish shipping
    # every group (hot_handoffs bumps once, at the end), then replay
    # the hot prompt on every adopter: byte-identity must hold on all
    # of them, and the prefix's owner must answer it off the adopted
    # blocks (radix hit) on at least one.
    wait_until = time.time() + 30
    while time.time() < wait_until and \
            fleet.lb.lb_stats().get('hot_handoffs', 0) < 1:
        time.sleep(0.05)
    survivors = [r for r in fleet.replicas if r is not busy]
    adopters = [r for r in survivors
                if r.server.engine.handoff_stats.get('adopted', 0) > 0]
    if not adopters:
        bad.append('DRAIN: no survivor adopted the hot set')
    elif hot_ref is not None:
        radix_hits = 0
        for adopter in adopters:
            hits0 = adopter.server.engine.radix_stats['hits']
            try:
                done = _finish_of(_stream_generate(
                    adopter.port, {'tokens': hot + [90],
                                   'max_new_tokens': 3, 'stream': True}))
                if done['output_tokens'] != hot_ref:
                    bad.append('DRAIN: hot replay diverged on the adopter')
                if adopter.server.engine.radix_stats['hits'] > hits0:
                    radix_hits += 1
            except RuntimeError as e:
                bad.append(f'DRAIN: hot replay failed: {e}')
        if radix_hits == 0:
            bad.append('DRAIN: hot replay missed the adopted radix')
    conn = HTTPConnection('127.0.0.1', busy.port, timeout=10)
    conn.request('POST', '/drain', body=b'{"cancel": true}')
    conn.getresponse()
    conn.close()
    return bad


def _stream_with_retry(port: int, payload: dict, wall_s: float = 90.0):
    """Stream through an LB that may be mid-restart: connection-level
    errors and severed streams retry (greedy decode is deterministic,
    so a from-scratch reissue yields identical tokens).  Returns
    (terminal_event, attempts)."""
    deadline = time.time() + wall_s
    attempts, last = 0, None
    while time.time() < deadline:
        attempts += 1
        try:
            events = _stream_generate(port, payload, timeout=30)
            done = [e for e in events if e.get('done')]
            if len(done) == 1 and \
                    done[0].get('finish_reason') in ('length', 'eos'):
                return done[0], attempts
            last = RuntimeError(
                f'incomplete stream ({len(done)} terminal events, '
                f'finish={done[0].get("finish_reason") if done else None})')
        except (OSError, RuntimeError) as e:
            last = e
        time.sleep(0.2)
    raise RuntimeError(f'never completed after {attempts} attempts: {last}')


def _lb_restart_exercise(fleet, references, n_requests: int) -> list:
    """Kill the LB mid-traffic, restart it on the same port with the
    journal re-adopted: zero requests lost, every answer
    byte-identical."""
    bad, results = [], {}
    lock = threading.Lock()

    def worker(idx):
        try:
            done, attempts = _stream_with_retry(fleet.lb_port,
                                                _request_spec(idx))
            with lock:
                results[idx] = (done['output_tokens'], attempts)
        except Exception as e:  # noqa: BLE001
            with lock:
                bad.append(f'LB-restart request {idx}: {e}')

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n_requests)]
    for th in threads:
        th.start()
    time.sleep(0.4)   # let streams get genuinely in flight
    fleet.kill_lb()
    time.sleep(0.3)   # clients live through the dead window
    fleet.restart_lb()
    for th in threads:
        th.join(120)
        if th.is_alive():
            bad.append('LB-restart: client hung')
    retried = sum(1 for _, n in results.values() if n > 1)
    for idx, (tokens, _) in sorted(results.items()):
        if tokens != references[idx]:
            bad.append(f'LB-restart: request {idx} diverged')
    if len(results) + len(bad) < n_requests:
        bad.append(f'LB-restart: only {len(results)}/{n_requests} '
                   'requests accounted for')
    stats = fleet.lb.lb_stats()
    if stats.get('adopted_unverified'):
        bad.append('LB-restart: journal-adopted replicas never '
                   f're-verified: {stats["adopted_unverified"]}')
    print(f'  lb-restart: kills={fleet.lb_kills} '
          f'restarts={fleet.lb_restarts} retried_clients={retried} '
          f'journal_age_s={stats.get("journal_age_s")} '
          f'{"FAIL" if bad else "ok"}')
    return bad


def _probation_exercise(fleet, references, window_s: float = 45.0) -> list:
    """Degrade one replica's network path (alive, answering probes,
    crawling responses) and require the LB's gray-failure track to put
    it in probation within the detection window — with every request
    routed through the rot still byte-identical."""
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site='net_degrade', prob=1.0, delay_s=0.4,
                  jitter_s=0.1),
    ])
    proxy = fleet.degrade_one(0, plan, seed=0)
    bad, stop = [], threading.Event()
    lock = threading.Lock()

    def lane(lane_id):
        i = lane_id
        while not stop.is_set():
            idx = i % 5
            i += 3
            try:
                done, _ = _stream_with_retry(fleet.lb_port,
                                             _request_spec(idx),
                                             wall_s=30)
                if done['output_tokens'] != references[idx]:
                    with lock:
                        bad.append(f'probation: request {idx} diverged '
                                   'through the degraded path')
            except RuntimeError as e:
                with lock:
                    bad.append(f'probation traffic: {e}')
                return

    # Three concurrent lanes so least-load routing spreads TTFT samples
    # across the fleet (probation compares against the fleet median —
    # it needs at least two replicas with an EWMA).
    lanes = [threading.Thread(target=lane, args=(k,), daemon=True)
             for k in range(3)]
    for th in lanes:
        th.start()
    deadline = time.time() + window_s
    probation = []
    while time.time() < deadline and not bad:
        probation = fleet.lb.lb_stats()['probation_replicas']
        # Wait for the DEGRADED replica specifically: another replica
        # entering probation (e.g. TTFT inflated by queuing behind the
        # rot) is not detection.
        if proxy.url in probation:
            break
        time.sleep(0.2)
    detect_wall = window_s - max(0.0, deadline - time.time())
    stop.set()
    for th in lanes:
        th.join(60)
    if proxy.url not in probation:
        bad.append(f'probation: degraded replica not ejected within '
                   f'{window_s}s (probation={probation}, '
                   f'delayed_chunks={proxy.chunks_delayed})')
    if proxy.chunks_delayed == 0:
        bad.append('probation: degrade proxy never fired')
    print(f'  probation: detected_in={detect_wall:.1f}s '
          f'delayed_chunks={proxy.chunks_delayed} '
          f'{"FAIL" if bad else "ok"}')
    return bad


def multi_replica_sweep(n_replicas: int, seeds, n_requests: int,
                        policy_name: str = 'least_load') -> int:
    import tempfile

    from skypilot_tpu.infer.chaos import ChaosFleet, SeededKiller

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    # Mixed fleet: the last replica runs tensor-parallel (tp=2) when
    # the platform has the chips — the LB, breaker, failover, and the
    # sanitizers must treat a head-sharded replica exactly like its
    # single-chip peers (byte-identical streams, same wire surface).
    tp_last = 2 if (n_replicas > 1 and len(jax.devices()) >= 2) else 0
    factories = [_replica_engine] * (n_replicas - 1) + \
        [functools.partial(_replica_engine, tp=tp_last)]
    print(f'replica chaos: {n_replicas} replicas seeds={seeds} '
          f'requests/episode={n_requests} policy={policy_name} '
          f'tp_last={tp_last or 1}')
    journal = os.path.join(tempfile.mkdtemp(prefix='chaos-lb-'),
                           'lb_journal.jsonl')
    fleet = ChaosFleet(factories, n_replicas,
                       policy_name=policy_name, journal_path=journal)
    fleet.start()
    failures = []
    try:
        # Fault-free pass = the byte-exact reference per request spec.
        references = {}
        for i in range(max(n_requests, 5)):
            done = _finish_of(_stream_generate(fleet.lb.port,
                                               _request_spec(i)))
            references[i] = done['output_tokens']

        for seed in seeds:
            t0 = time.time()
            killer = SeededKiller(fleet, FaultPlan(seed=seed, specs=[
                FaultSpec(site='replica_kill', prob=0.02, max_fires=2),
            ]))
            killer.start()
            bad, done_stats = [], {'resumed': 0}
            lock = threading.Lock()

            def worker(idx, bad=bad, done_stats=done_stats, lock=lock):
                try:
                    events = _stream_generate(fleet.lb.port,
                                              _request_spec(idx))
                    done = _finish_of(events)
                    if done.get('finish_reason') not in ('length', 'eos'):
                        raise RuntimeError(
                            f'finish_reason={done.get("finish_reason")} '
                            f'error={done.get("error")!r}')
                    if done['output_tokens'] != references[idx]:
                        raise RuntimeError(
                            f'tokens diverged: {done["output_tokens"]} '
                            f'!= {references[idx]}')
                    with lock:
                        done_stats['resumed'] += bool(done.get('resumed'))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        bad.append(f'seed={seed} request {idx}: {e}')

            # Two client lanes keep replicas busy so kills land
            # mid-stream, not between requests.
            threads = [threading.Thread(target=worker, args=(i,),
                                        daemon=True)
                       for i in range(n_requests)]
            for lane in range(0, n_requests, 2):
                batch = threads[lane:lane + 2]
                for th in batch:
                    th.start()
                for th in batch:
                    th.join(90)
                    if th.is_alive():
                        bad.append(f'seed={seed}: client hung')
            killer.stop()
            fleet.respawn_dead()
            stats = fleet.lb.lb_stats()
            print(f'  seed={seed}: kills={killer.kills} '
                  f'resumed={done_stats["resumed"]} '
                  f'failovers={stats["failovers"]} '
                  f'wall={time.time() - t0:.1f}s '
                  f'{"FAIL" if bad else "ok"}')
            failures += bad
            # Let probes re-admit the respawned replicas.
            settle = time.time() + 15
            while time.time() < settle:
                if not fleet.lb.lb_stats()['breaker_open_now']:
                    break
                time.sleep(0.05)

        # Each leg tests ONE mechanism.  The kill episodes leave gray-
        # failure evidence behind (TTFT EWMAs spiked by mid-stream
        # failovers can hold a survivor in probation indefinitely once
        # it stops drawing traffic), and a survivor stuck in probation
        # diverts the drain leg's hot replay away from the replica that
        # adopted the radix — so the evidence is explicitly reset at
        # each leg boundary, exactly like an operator closing out a
        # maintenance window.
        fleet.lb.reset_gray_state()
        failures += _drain_exercise(fleet, references)
        fleet.lb.reset_gray_state()
        failures += _lb_restart_exercise(fleet, references,
                                         n_requests=min(6, n_requests))
        fleet.lb.reset_gray_state()
        failures += _probation_exercise(fleet, references)
        print(f'  lb stats: {fleet.lb.lb_stats()}')
    finally:
        fleet.stop()
    if failures:
        print('REPLICA CHAOS FAILED:')
        for f in failures:
            print(f'  {f}')
        return 1
    print('replica chaos: PASS')
    return 0


# ------------------------------------------------------- batch sweep


def _wait_for(pred, timeout_s: float, what: str):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return None
        time.sleep(0.05)
    return f'batch: timed out waiting for {what}'


def batch_sweep(n_replicas: int, n_rows: int) -> int:
    """Durable-batch chaos leg (PR 20): one journaled batch job run
    twice — fault-free, then with every actor killed mid-flight
    (replica kill, LB kill + warm restart, coordinator crash-stop +
    resume on the same journal) — and the final output file must be
    byte-identical with zero lost rows and zero determinism
    violations.  Duplicates are allowed to OCCUR (that is the crash
    replay) but must dedup against the spooled digest instead of
    double-writing."""
    import tempfile

    from skypilot_tpu.infer.chaos import ChaosFleet
    from skypilot_tpu.serve.batch import BatchCoordinator

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    prompts = [[(5 * i + j) % 97 + 1 for j in range(3 + i % 5)]
               for i in range(n_rows)]
    print(f'batch chaos: {n_replicas} replicas rows={n_rows}')

    def fresh_fleet() -> ChaosFleet:
        journal = os.path.join(
            tempfile.mkdtemp(prefix='chaos-batch-lb-'),
            'lb_journal.jsonl')
        # Slower decode (bigger stall) + a single row worker below:
        # each kill must land while the job is genuinely mid-flight,
        # not in the gap between an instant job and the choreography.
        fleet = ChaosFleet(functools.partial(_replica_engine,
                                             stall_s=0.08),
                           n_replicas, journal_path=journal)
        fleet.start()
        return fleet

    failures = []

    # ---- fault-free pass: the byte-exact reference ------------------
    fleet = fresh_fleet()
    ref_bytes = None
    try:
        d = tempfile.mkdtemp(prefix='chaos-batch-ref-')
        coord = BatchCoordinator(os.path.join(d, 'batch.jsonl'),
                                 fleet.lb_port,
                                 spool_dir=os.path.join(d, 'spool'),
                                 row_workers=2)
        jid = coord.submit(prompts, 10,
                           completion_window_s=EPISODE_WALL_S,
                           job_id='chaosjob')
        if not coord.join(jid, EPISODE_WALL_S):
            failures.append('batch: fault-free job never finished: '
                            f'{coord.status(jid)}')
        else:
            st = coord.status(jid)
            if st['state'] != 'done':
                failures.append(f'batch: fault-free run ended {st}')
            with open(coord.result_path(jid), 'rb') as fh:
                ref_bytes = fh.read()
        coord.stop()
    finally:
        fleet.stop()
    if failures:
        print('BATCH CHAOS FAILED (reference pass):')
        for f in failures:
            print(f'  {f}')
        return 1

    # ---- chaos pass: same job, every actor dies mid-flight ----------
    fleet = fresh_fleet()
    try:
        d = tempfile.mkdtemp(prefix='chaos-batch-run-')
        jpath = os.path.join(d, 'batch.jsonl')
        spool = os.path.join(d, 'spool')
        # ONE row worker: rows dispatch strictly serially, so the
        # choreography below (each wait is a row-count threshold)
        # always finds the job mid-flight.
        coord = BatchCoordinator(jpath, fleet.lb_port, spool_dir=spool,
                                 row_workers=1)
        jid = coord.submit(prompts, 10,
                           completion_window_s=3 * EPISODE_WALL_S,
                           job_id='chaosjob')

        def done_rows():
            return coord.status(jid)['completed']

        # 1. Replica killed mid-job: the LB fails the stream over;
        #    only unfinished rows are ever (re)dispatched.  The dead
        #    replica stays down until the successor coordinator is up
        #    (respawn compiles a fresh engine, which takes long enough
        #    for a small job to finish — the later kills must still
        #    land mid-flight).
        err = _wait_for(lambda: done_rows() >= 2, 60, 'first rows')
        if err:
            failures.append(err)
        if fleet.kill_one() is None:
            failures.append('batch: no replica available to kill')
        marker = done_rows()
        err = _wait_for(lambda: done_rows() > marker, 60,
                        'progress past the replica kill')
        if err:
            failures.append(err)

        # 2. LB killed mid-row, restarted on the same port: the row
        #    transport retries through the outage and the restarted LB
        #    re-adopts the orphaned row leases from its journal
        #    (adoption runs in the constructor, so the counter is
        #    valid the moment restart_lb returns).
        err = _wait_for(
            lambda: fleet.lb_stats()['batch_rows_inflight'] >= 1,
            60, 'a batch row in flight at the LB')
        if err:
            failures.append(err)
        fleet.kill_lb()
        time.sleep(0.3)
        fleet.restart_lb(wait_adopted=False)
        lb_stats = fleet.lb_stats()
        if lb_stats.get('batch_leases_adopted', 0) < 1:
            failures.append('batch: restarted LB adopted no row '
                            f'leases (stats={lb_stats})')

        # 3. Coordinator (the controller-side actor) crash-stopped
        #    mid-job: a successor on the same journal path RESUMES —
        #    completed rows are recognised by digest and never re-run.
        before = coord.status(jid)
        coord.stop()
        if before['state'] != 'running':
            failures.append('batch: job finished before the '
                            f'coordinator crash ({before}) — '
                            'resume leg proved nothing')
        coord2 = BatchCoordinator(jpath, fleet.lb_port,
                                  spool_dir=spool, row_workers=2)
        fleet.respawn_dead()   # capacity back while the successor runs
        resumed = coord2.status(jid)
        if resumed['completed'] < before['completed']:
            failures.append(
                'batch: resume lost completed rows '
                f'({resumed["completed"]} < {before["completed"]})')
        if not coord2.join(jid, 2 * EPISODE_WALL_S):
            failures.append('batch: resumed job never finished: '
                            f'{coord2.status(jid)}')
        final = coord2.status(jid)
        if final['state'] != 'done' or final['completed'] != n_rows:
            failures.append(f'batch: final status {final}')
        if final['determinism_violations']:
            failures.append('batch: determinism violations: '
                            f'{final["determinism_violations"]}')
        chaos_bytes = None
        try:
            with open(coord2.result_path(jid), 'rb') as fh:
                chaos_bytes = fh.read()
        except OSError as e:
            failures.append(f'batch: no output file: {e}')
        if ref_bytes is not None and chaos_bytes != ref_bytes:
            failures.append('batch: chaos output is not '
                            'byte-identical to the fault-free run')
        print(f'  batch: rows={final["completed"]}/{n_rows} '
              f'retries={final["retries"]} dups={final["duplicates"]} '
              f'resumed_from={before["completed"]} '
              f'leases_adopted='
              f'{lb_stats.get("batch_leases_adopted")} '
              f'{"FAIL" if failures else "ok"}')
        coord2.stop()
    finally:
        fleet.stop()
    if failures:
        print('BATCH CHAOS FAILED:')
        for f in failures:
            print(f'  {f}')
        return 1
    print('batch chaos: PASS')
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--seeds', type=int, nargs='+', default=[0, 1, 2],
                    help='fault-plan seeds to sweep')
    ap.add_argument('--requests', type=int, default=12)
    ap.add_argument('--multi-replica', type=int, default=0,
                    metavar='N',
                    help='replica-plane sweep with N killable replicas '
                         'behind the load balancer (0 = engine sweep)')
    ap.add_argument('--policy', default='least_load',
                    help='LB policy for --multi-replica (byte-identity '
                         'must hold under ANY routing policy)')
    ap.add_argument('--batch', action='store_true',
                    help='durable batch-job chaos leg: kill a replica, '
                         'the LB, and the coordinator mid-job; the '
                         'final output must be byte-identical to the '
                         'fault-free run with zero lost/duplicated '
                         'rows')
    args = ap.parse_args()
    if args.batch:
        return batch_sweep(args.multi_replica or 3,
                           n_rows=2 * args.requests)
    if args.multi_replica:
        return multi_replica_sweep(args.multi_replica, args.seeds,
                                   args.requests, args.policy)
    print(f'chaos smoke: seeds={args.seeds} '
          f'requests/episode={args.requests}')
    eng = build_engine()
    failures = []
    for seed in args.seeds:
        failures += episode(eng, seed, args.requests)
    if failures:
        print('CHAOS SMOKE FAILED:')
        for f in failures:
            print(f'  {f}')
        return 1
    print('chaos smoke: PASS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
