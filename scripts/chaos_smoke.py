#!/usr/bin/env python
"""Chaos smoke: seeded fault sweep over the small model.

Tier-1 companion to tests/test_faults.py: where the tests pin exact
scenarios (one fault, one assertion), this sweep arms a *mixture* of
probabilistic faults across every injection site and checks the two
properties that must hold under ANY fault sequence:

  1. **No hang** — every serving episode drains within its wall bound
     (nothing waits on a dead loop or a stuck allocator).
  2. **Full request accounting** — every submitted request gets exactly
     one terminal result (ok / error / deadline), and the paged block
     pool balances at drain (all blocks free, refcounts zero).

Probabilistic specs draw from per-spec seeded streams (FaultPlan), so
a failing seed reproduces exactly:  scripts/chaos_smoke.py --seeds 3

Exit code: 0 = all episodes passed, 1 = any property violated.
"""
import argparse
import copy
import queue
import sys
import threading
import time

sys.path.insert(0, '.')

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import (FaultPlan, FaultSpec, InferConfig,
                                InferenceEngine, Request)
from skypilot_tpu.models.llama import LlamaConfig

EPISODE_WALL_S = 120.0


def build_engine() -> InferenceEngine:
    mc = LlamaConfig(name='chaos-smoke', vocab_size=101, hidden_size=32,
                     intermediate_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=128,
                     tie_embeddings=True, dtype='float32')
    cfg = InferConfig(num_slots=4, max_cache_len=64,
                      prefill_buckets=(8, 16, 32), max_new_tokens=8,
                      cache_dtype=jnp.float32, kv_block_size=8)
    return InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0))


def make_plan(seed: int) -> FaultPlan:
    """A bit of everything: attributed and unattributed dispatch
    faults, allocator pressure, NaN lanes, stalls, and loop death."""
    return FaultPlan(seed=seed, specs=[
        FaultSpec(site='decode_step', prob=0.10, slot=1, max_fires=2),
        FaultSpec(site='decode_step', prob=0.04, max_fires=1),
        FaultSpec(site='prefill', prob=0.10, max_fires=2),
        FaultSpec(site='chunk_round', prob=0.10, max_fires=1),
        FaultSpec(site='block_alloc', prob=0.15, max_fires=4),
        FaultSpec(site='nonfinite_logits', prob=0.08, slot=0,
                  max_fires=2),
        FaultSpec(site='stall', prob=0.10, stall_s=0.05),
        FaultSpec(site='serve_loop', prob=0.05, max_fires=2),
    ])


def make_requests(n: int):
    reqs = []
    for i in range(n):
        toks = [(5 * i + j) % 97 + 1 for j in range(3 + i % 5)]
        reqs.append(Request(
            request_id=f'r{i}', tokens=toks,
            max_new_tokens=4 + i % 12,
            # Every 5th request carries a (generous) deadline so the
            # eviction path runs inside the sweep too.
            deadline_s=30.0 if i % 5 == 0 else None))
    return reqs


def episode(eng: InferenceEngine, seed: int, n: int) -> list:
    """One serving episode under an armed plan; returns violations."""
    plan = make_plan(seed)
    reqs = make_requests(n)
    results, q, stop = {}, queue.Queue(), threading.Event()
    for r in reqs:
        q.put(copy.deepcopy(r))
    eng.arm_faults(plan)
    loop_exc = []

    def run():
        try:
            eng.generate_stream(
                q, lambda res: results.setdefault(res.request_id, res),
                stop)
        except Exception as e:  # supervisor gave up: legal iff every
            loop_exc.append(e)  # request was still accounted for
    t = threading.Thread(target=run, daemon=True)
    t0 = time.time()
    t.start()
    try:
        while len(results) < n and time.time() - t0 < EPISODE_WALL_S:
            if loop_exc and len(results) >= n:
                break
            time.sleep(0.02)
    finally:
        stop.set()
        t.join(timeout=30)
        eng.disarm_faults()

    bad = []
    if t.is_alive():
        bad.append('HANG: serving loop did not stop')
    if len(results) != n:
        missing = sorted(set(r.request_id for r in reqs) - set(results))
        bad.append(f'ACCOUNTING: {len(results)}/{n} results; '
                   f'missing {missing}')
    reasons = {}
    for res in results.values():
        reasons[res.finish_reason] = reasons.get(res.finish_reason,
                                                 0) + 1
        if res.finish_reason not in ('length', 'eos', 'error',
                                     'deadline'):
            bad.append(f'BAD finish_reason {res.finish_reason!r} '
                       f'for {res.request_id}')
    if eng._paged:
        if len(eng._free_blocks) != eng._num_blocks - 1 or \
                eng._block_refs[0] != 1 or \
                not (eng._block_refs[1:] == 0).all():
            bad.append(
                f'BLOCK LEAK: {len(eng._free_blocks)} free of '
                f'{eng._num_blocks - 1}, refs={eng._block_refs.tolist()}')
    print(f'  seed={seed}: {reasons} wall={time.time() - t0:.1f}s '
          f'fired={plan.stats()["fired"]} '
          f'counters={eng.fault_stats} '
          f'{"terminal-giveup " if loop_exc else ""}'
          f'{"FAIL" if bad else "ok"}')
    return bad


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--seeds', type=int, nargs='+', default=[0, 1, 2],
                    help='fault-plan seeds to sweep')
    ap.add_argument('--requests', type=int, default=12)
    args = ap.parse_args()
    print(f'chaos smoke: seeds={args.seeds} '
          f'requests/episode={args.requests}')
    eng = build_engine()
    failures = []
    for seed in args.seeds:
        failures += episode(eng, seed, args.requests)
    if failures:
        print('CHAOS SMOKE FAILED:')
        for f in failures:
            print(f'  {f}')
        return 1
    print('chaos smoke: PASS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
