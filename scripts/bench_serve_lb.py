#!/usr/bin/env python3
"""Serve-PLANE benchmark: Llama-2-7B int8 behind the real serve stack.

VERDICT r2 weak #1 / next #1: the r2 serving numbers came from
`InferenceEngine.benchmark_serving` in-process; the reference anchor
(JetStream Llama-2-7B on v6e-8: 11.42 req/s, TTFT p50 1.83 s —
/root/reference/examples/tpu/v6e/README.md:114-127) was measured through
its full serving stack.  This script measures OURS the same way:

  serve up (controller + prober + load balancer, local cloud = this
  machine, engine on the real chip) -> Poisson arrivals POSTed to the
  **LB endpoint** with stream=True -> client-side TTFT = first SSE
  token event, so the number includes LB proxy hop, SSE framing, and
  probe interference.

Writes rows into BENCH_SERVE_r03.json (alongside engine-direct rows for
the plane-vs-engine overhead comparison) when run with --out.

``--failover`` runs the replica-fault section instead: a two-replica
in-process fleet behind the LB, killing the serving replica after the
first relayed SSE chunk, and reporting the p50/p99 latency a resumed
stream pays over a clean one (the cost of detection + continuation
replay).  CPU-friendly (tiny model); writes BENCH_SERVE_r06.json.

Usage:
  python scripts/bench_serve_lb.py --qps 2.0 --qps 3.5 --out BENCH_SERVE_r03.json
  python scripts/bench_serve_lb.py --failover --out BENCH_SERVE_r06.json
"""
import argparse
import json
import statistics
import sys
import threading
import time
import urllib.request
from http.client import HTTPConnection

sys.path.insert(0, '.')

PROMPT_LEN = 219      # mirrors the reference JetStream workload shape
NEW_TOKENS = 188


def _post_stream(endpoint: str, tokens, max_new: int):
    """POST /generate stream=True; returns (ttft_s, latency_s, n_out)."""
    body = json.dumps({'tokens': tokens, 'max_new_tokens': max_new,
                       'stream': True}).encode()
    req = urllib.request.Request(
        endpoint + '/generate', data=body,
        headers={'Content-Type': 'application/json'})
    t0 = time.time()
    ttft = None
    n_out = 0
    with urllib.request.urlopen(req, timeout=600) as resp:
        if resp.status != 200:
            raise RuntimeError(f'HTTP {resp.status}')
        for raw in resp:
            line = raw.decode('utf-8', 'replace').strip()
            if not line.startswith('data: '):
                continue
            msg = json.loads(line[len('data: '):])
            if msg.get('done'):
                if msg.get('finish_reason') == 'error':
                    raise RuntimeError(msg.get('error', 'stream error'))
                n_out = len(msg.get('output_tokens', []))
                break
            if ttft is None and msg.get('tokens'):
                ttft = time.time() - t0
            n_out += len(msg.get('tokens', []))
    return (ttft if ttft is not None else time.time() - t0,
            time.time() - t0, n_out)


def run_sweep_row(endpoint: str, qps: float, num_requests: int,
                  vocab: int = 32000, seed: int = 0):
    import numpy as np
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, size=num_requests)
    prompts = [rng.integers(4, vocab, size=PROMPT_LEN).tolist()
               for _ in range(num_requests)]
    results = [None] * num_requests
    errors = []
    sheds = []
    threads = []

    def one(i):
        import urllib.error
        try:
            results[i] = _post_stream(endpoint, prompts[i], NEW_TOKENS)
        except urllib.error.HTTPError as e:
            if e.code == 429:
                sheds.append((i, e.headers.get('Retry-After')))
            else:
                errors.append((i, f'HTTP {e.code}'))
        except Exception as e:  # pylint: disable=broad-except
            errors.append((i, str(e)[:200]))

    t_start = time.time()
    for i in range(num_requests):
        time.sleep(float(gaps[i]))
        t = threading.Thread(target=one, args=(i,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=900)
    elapsed = time.time() - t_start
    done = [r for r in results if r is not None]
    if not done:
        raise RuntimeError(f'no request completed; errors: {errors[:3]}')
    ttfts = sorted(r[0] for r in done)
    lats = [r[1] for r in done]
    outs = sum(r[2] for r in done)
    tpots = sorted((r[1] - r[0]) / max(r[2] - 1, 1) for r in done)

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(len(xs) * p))]

    return {
        'offered_qps': qps,
        'completed': len(done),
        'shed_429': len(sheds),
        'shed_rate': len(sheds) / num_requests,
        'errors': len(errors),
        'requests_per_second': len(done) / elapsed,
        'output_tokens_per_second': outs / elapsed,
        'ttft_median_s': statistics.median(ttfts),
        'ttft_p99_s': pct(ttfts, 0.99),
        'tpot_median_s': statistics.median(tpots),
        'tpot_p99_s': pct(tpots, 0.99),
        'latency_median_s': statistics.median(sorted(lats)),
        'elapsed_s': elapsed,
        'measured_at': 'load_balancer_endpoint',
    }


# ------------------------------------------------- failover section


def _failover_stream(port: int, payload: dict, on_first_chunk=None):
    """Stream /generate via the LB; returns (latency_s, done_event).
    Calls on_first_chunk after the first token event arrives."""
    conn = HTTPConnection('127.0.0.1', port, timeout=120)
    t0 = time.time()
    try:
        conn.request('POST', '/generate',
                     body=json.dumps(payload).encode(),
                     headers={'Content-Type': 'application/json'})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f'HTTP {resp.status}')
        buf, fired, done = b'', False, None
        while done is None:
            chunk = resp.read1(65536)
            if not chunk:
                raise RuntimeError('stream ended without done event')
            buf += chunk
            while b'\n\n' in buf and done is None:
                ev, buf = buf.split(b'\n\n', 1)
                for line in ev.split(b'\n'):
                    if line.startswith(b'data: '):
                        msg = json.loads(line[6:])
                        if msg.get('done'):
                            done = msg
            if not fired and on_first_chunk is not None:
                fired = True
                on_first_chunk()
        return time.time() - t0, done
    finally:
        conn.close()


def run_failover_bench(iters: int, out: str) -> None:
    """Clean vs killed-and-resumed stream latency through the LB."""
    import os

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import FaultPlan, FaultSpec, InferConfig
    from skypilot_tpu.infer.chaos import ChaosFleet
    from skypilot_tpu.infer.engine import InferenceEngine
    from skypilot_tpu.models.llama import LlamaConfig

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    mc = LlamaConfig(name='lbbench-t', vocab_size=101, hidden_size=32,
                     intermediate_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=128,
                     tie_embeddings=True, dtype='float32')
    cfg = InferConfig(num_slots=4, max_cache_len=64,
                      prefill_buckets=(8, 16, 32), max_new_tokens=32,
                      cache_dtype=jnp.float32, decode_steps=4,
                      kv_block_size=8, auto_prefix_cache=True,
                      host_kv_bytes=32 << 20)

    def make_engine():
        eng = InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0))
        # Deterministic warmup FIRST (the same helper serve-plane
        # boots call): every prefill/suffix bucket compiles before the
        # stall fault is armed, so no compile ever lands inside a
        # measured stream — the r17 warm-boot story, re-measured here.
        eng.warmup()
        # Stretch the stream across loop iterations so the mid-stream
        # kill has a mid-stream to land in (sleep only; both arms of
        # the comparison pay it equally).
        eng.arm_faults(FaultPlan(seed=0, specs=[
            FaultSpec(site='stall', prob=1.0, stall_s=0.04)]))
        return eng

    payload = {'tokens': [3, 14, 15, 9, 2, 6], 'max_new_tokens': 24,
               'stream': True}
    fleet = ChaosFleet(make_engine, 2)
    fleet.start()
    try:
        def settle():
            deadline = time.time() + 60
            while time.time() < deadline:
                if len(fleet.live_replicas()) == 2 and not \
                        fleet.lb.lb_stats()['breaker_open_now']:
                    return
                time.sleep(0.05)
            raise TimeoutError('fleet never settled')

        _, ref_done = _failover_stream(fleet.lb.port, payload)
        reference = ref_done['output_tokens']

        clean, resumed = [], []
        for _ in range(iters):
            lat, done = _failover_stream(fleet.lb.port, payload)
            assert done['output_tokens'] == reference
            clean.append(lat)
        for i in range(iters):
            settle()
            lat, done = _failover_stream(
                fleet.lb.port, payload,
                on_first_chunk=lambda: fleet.kill_one())
            if not done.get('resumed'):
                raise RuntimeError(
                    f'iteration {i}: stream was not resumed ({done})')
            if done['output_tokens'] != reference:
                raise RuntimeError(f'iteration {i}: tokens diverged')
            resumed.append(lat)
            fleet.respawn_dead()
        # Warm-drain handoff: cache a hot prefix DIRECTLY on one
        # replica (so the survivor has never seen it), drain that
        # replica, wait for the LB's hot-set handoff to land on the
        # survivor, then compare the survivor's TTFT for the handed-off
        # prefix (suffix-only prefill off adopted blocks) against cold
        # same-shape prefixes (full re-prefill).
        settle()
        hot = [5] * 24
        src, dst = fleet.replicas[0], fleet.replicas[1]
        for k in range(3):
            _affinity_ttft_stream(src.port, hot + [9 + k], max_new=4)
        urllib.request.urlopen(urllib.request.Request(
            f'http://127.0.0.1:{src.port}/drain', data=b'{}',
            headers={'Content-Type': 'application/json'}), timeout=10)
        deadline = time.time() + 30
        while time.time() < deadline and \
                dst.server.engine.handoff_stats['adopted'] == 0:
            time.sleep(0.1)
        adopted = dst.server.engine.handoff_stats['adopted']
        radix0 = dict(dst.server.engine.radix_stats)
        hot_ttfts = [_affinity_ttft_stream(fleet.lb.port,
                                           hot + [40 + k],
                                           max_new=4)[0]
                     for k in range(4)]
        radix1 = dict(dst.server.engine.radix_stats)
        cold_ttfts = [_affinity_ttft_stream(fleet.lb.port,
                                            [50 + k] * 24 + [9],
                                            max_new=4)[0]
                      for k in range(4)]
        drain = {
            'adopted_blocks': adopted,
            # Every post-drain hot request must match the handed-off
            # prefix on the survivor (suffix-only prefill); the cold
            # control full-prefills.  At this tiny geometry the width
            # difference sits below dispatch noise — the TTFT
            # direction at compute-bound scale is the
            # measured_tiny_sweep in BENCH_MICRO_r10.json.
            'survivor_hot_radix_hits': radix1['hits'] - radix0['hits'],
            'prefill_tokens_avoided':
                radix1['tokens_reused'] - radix0['tokens_reused'],
            'survivor_hot_ttft_p50_s': statistics.median(hot_ttfts),
            'survivor_cold_ttft_p50_s': statistics.median(cold_ttfts),
        }
        stats = fleet.lb.lb_stats()
    finally:
        fleet.stop()

    def pct(xs, p):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * p))]

    row = {
        'iters': iters,
        'clean_p50_s': statistics.median(clean),
        'clean_p99_s': pct(clean, 0.99),
        'failover_p50_s': statistics.median(resumed),
        'failover_p99_s': pct(resumed, 0.99),
        'added_p50_s': statistics.median(resumed) -
                       statistics.median(clean),
        'added_p99_s': pct(resumed, 0.99) - pct(clean, 0.99),
        'streams_resumed': stats['streams_resumed'],
        'failovers': stats['failovers'],
        'warm_boot': True,
        'hot_handoffs': stats['hot_handoffs'],
        'handoff_prefixes': stats['handoff_prefixes'],
        'drain_handoff': drain,
        'model': 'tiny-cpu',
        'measured_at': 'load_balancer_endpoint',
    }
    print(json.dumps(row, indent=2), flush=True)
    try:
        doc = json.load(open(out))
    except (FileNotFoundError, ValueError):
        doc = {}
    doc.setdefault('failover', [])
    doc['failover'].append(row)
    json.dump(doc, open(out, 'w'), indent=2)
    print(f'wrote {out}')


# ------------------------------------------------- affinity section


def _affinity_prompts(groups: int, per_group: int, overlap: float,
                      prompt_len: int = 384, block: int = 16):
    """`groups` families of prompts sharing a block-aligned head of
    ~overlap * prompt_len tokens, each with a distinct tail."""
    shared = max(block, int(prompt_len * overlap) // block * block)
    specs = []
    for g in range(groups):
        head = [(g * 131 + 7 * j) % 97 + 1 for j in range(shared)]
        for r in range(per_group):
            tail = [(g * 17 + r * 29 + 3 * j) % 97 + 1
                    for j in range(prompt_len - shared)]
            specs.append({'group': g, 'req': r, 'tokens': head + tail})
    return specs


def _affinity_ttft_stream(port: int, tokens, max_new: int = 8):
    """Returns (ttft_s, output_tokens) for one stream through the LB."""
    conn = HTTPConnection('127.0.0.1', port, timeout=300)
    t0 = time.time()
    try:
        conn.request('POST', '/generate',
                     body=json.dumps({'tokens': tokens,
                                      'max_new_tokens': max_new,
                                      'stream': True}).encode(),
                     headers={'Content-Type': 'application/json'})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f'HTTP {resp.status}')
        buf, ttft, done = b'', None, None
        while done is None:
            chunk = resp.read1(65536)
            if not chunk:
                raise RuntimeError('stream ended without done event')
            buf += chunk
            while b'\n\n' in buf and done is None:
                ev, buf = buf.split(b'\n\n', 1)
                for line in ev.split(b'\n'):
                    if line.startswith(b'data: '):
                        msg = json.loads(line[6:])
                        if msg.get('done'):
                            done = msg
                        elif ttft is None and msg.get('tokens'):
                            ttft = time.time() - t0
        if done.get('finish_reason') not in ('length', 'eos'):
            raise RuntimeError(f'finish_reason={done.get("finish_reason")}'
                               f' error={done.get("error")!r}')
        return ttft if ttft is not None else time.time() - t0, \
            done['output_tokens']
    finally:
        conn.close()


def _run_affinity_arm(make_engine, n_replicas: int, policy: str,
                      specs, width: int):
    """One fleet arm: fresh replicas (cold radix trees), `width`
    concurrent client lanes draining the spec list in order.  Returns
    (ttfts_by_spec, outputs_by_spec, fleet_radix, policy_stats)."""
    import queue as queue_mod

    from skypilot_tpu.infer.chaos import ChaosFleet

    fleet = ChaosFleet(make_engine, n_replicas, policy_name=policy)
    fleet.start()
    try:
        ttfts, outputs = {}, {}
        q = queue_mod.Queue()
        for spec in specs:
            q.put(spec)
        errors = []

        def lane():
            while True:
                try:
                    spec = q.get_nowait()
                except queue_mod.Empty:
                    return
                key = (spec['group'], spec['req'])
                try:
                    ttfts[key], outputs[key] = _affinity_ttft_stream(
                        fleet.lb.port, spec['tokens'])
                except Exception as e:  # pylint: disable=broad-except
                    errors.append(f'{key}: {e}')

        lanes = [threading.Thread(target=lane, daemon=True)
                 for _ in range(width)]
        for t in lanes:
            t.start()
        for t in lanes:
            t.join(timeout=600)
        if errors:
            raise RuntimeError(f'affinity arm failed: {errors[:3]}')
        hits = lookups = 0
        for rep in fleet.replicas:
            radix = rep.server.engine.kv_health()['radix']
            hits += radix['hits']
            lookups += radix['lookups']
        return ttfts, outputs, \
            {'hits': hits, 'lookups': lookups,
             'hit_rate': hits / lookups if lookups else 0.0}, \
            fleet.lb.policy.stats()
    finally:
        fleet.stop()


def run_affinity_bench(out: str, n_replicas: int = 3, groups: int = 8,
                       per_group: int = 6,
                       overlaps=(0.5, 0.9)) -> None:
    """Shared-system-prompt TTFT sweep: prefix_affinity vs least_load
    through N replicas, with a single-replica arm as the radix-cache
    ceiling.  Each replica runs the radix tree (PR 4); blind balancing
    splits a prefix family across replicas so most requests pay a cold
    full prefill, while affinity routing sends a family to one replica
    — one cold miss, then fleet-wide hits.  Greedy outputs must be
    byte-identical across every arm (routing may NEVER change tokens).
    """
    import os

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig
    from skypilot_tpu.infer.engine import InferenceEngine
    from skypilot_tpu.models.llama import LlamaConfig

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    # Big enough that a 384-token cold prefill costs measurable CPU
    # time (the quantity radix hits avoid); small enough to stay a
    # laptop-class bench.
    mc = LlamaConfig(name='affinity-bench', vocab_size=101,
                     hidden_size=128, intermediate_size=256,
                     num_layers=4, num_heads=4, num_kv_heads=2,
                     max_seq_len=512, tie_embeddings=True,
                     dtype='float32')
    # The 192 bucket matters: a 50%-overlap match leaves a 192-token
    # suffix, and without a bucket that FITS beside the match
    # (start + bucket <= max_cache_len) the engine abandons the match
    # and full-prefills.
    cfg = InferConfig(num_slots=4, max_cache_len=448,
                      prefill_buckets=(64, 192, 448), max_new_tokens=8,
                      cache_dtype=jnp.float32, decode_steps=4,
                      kv_block_size=16, kv_blocks=384,
                      auto_prefix_cache=True)

    def make_engine():
        eng = InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0))
        # Deterministic warmup: the same helper serve-plane boots call.
        # Its suffix-bucket sweep covers the radix-hit shapes (suffix
        # 64 and 192 beside a cached block) the old per-replica
        # hand-warm loop compiled over HTTP — so no compile lands in a
        # measured TTFT, with no bench-local shape list to maintain.
        eng.warmup()
        return eng

    # Every arm sees the SAME offered load (one lane per fleet
    # replica).  On a shared-CPU bench host the engines multiplex one
    # core, so total compute is also equal across arms — the single
    # arm is then the genuine one-logical-cache ceiling and any gap to
    # it is routing/cache-partitioning loss, not a width or capacity
    # artifact.  One lane per replica keeps same-instant prefill
    # collisions (pure shared-core multiplexing a real multi-chip
    # fleet never pays) out of the fleet arms' p50.
    width = n_replicas
    arms = [('single_replica', 1, 'least_load', width),
            ('least_load', n_replicas, 'least_load', width),
            ('prefix_affinity', n_replicas, 'prefix_affinity', width)]
    rows, summary = [], []
    for overlap in overlaps:
        specs = _affinity_prompts(groups, per_group, overlap)
        # Interleave groups so concurrent lanes carry different
        # families (the least_load spray the policy must beat).
        specs.sort(key=lambda s: (s['req'], s['group']))
        arm_ttfts, arm_outputs = {}, {}
        for name, n, policy, width in arms:
            print(f'-- overlap={overlap} arm={name} ({n} replicas, '
                  f'{len(specs)} requests)', flush=True)
            ttfts, outputs, radix, pstats = _run_affinity_arm(
                make_engine, n, policy, specs, width)
            arm_ttfts[name], arm_outputs[name] = ttfts, outputs
            vals = sorted(ttfts.values())
            row = {
                'overlap': overlap,
                'arm': name,
                'n_replicas': n,
                'client_width': width,
                'groups': groups,
                'requests': len(specs),
                'ttft_p50_s': statistics.median(vals),
                'ttft_mean_s': statistics.mean(vals),
                'ttft_p99_s': vals[min(len(vals) - 1,
                                       int(len(vals) * 0.99))],
                'fleet_radix_hit_rate': radix['hit_rate'],
                'fleet_radix_hits': radix['hits'],
                'fleet_radix_lookups': radix['lookups'],
            }
            if policy == 'prefix_affinity':
                row['affinity_hits'] = pstats['affinity_hits']
                row['affinity_spills'] = pstats['affinity_spills']
            print(json.dumps(row), flush=True)
            rows.append(row)
        # Routing must never change tokens: every arm byte-identical.
        for name in ('least_load', 'prefix_affinity'):
            if arm_outputs[name] != arm_outputs['single_replica']:
                raise RuntimeError(
                    f'greedy outputs diverged between single_replica '
                    f'and {name} at overlap {overlap}')
        p50 = {name: statistics.median(sorted(arm_ttfts[name].values()))
               for name, *_ in arms}
        summary.append({
            'overlap': overlap,
            'speedup_vs_least_load':
                p50['least_load'] / p50['prefix_affinity'],
            'vs_single_replica':
                p50['prefix_affinity'] / p50['single_replica'],
            'outputs_byte_identical': True,
        })
        print(json.dumps(summary[-1]), flush=True)

    try:
        doc = json.load(open(out))
    except (FileNotFoundError, ValueError):
        doc = {}
    doc['affinity'] = {'rows': rows, 'summary': summary,
                       'model': 'tiny-cpu',
                       'measured_at': 'load_balancer_endpoint'}
    json.dump(doc, open(out, 'w'), indent=2)
    print(f'wrote {out}')


# --------------------------------------------- gray-failure section


def _gray_env(overrides: dict):
    """Apply SKYTPU_LB_* knob overrides for one arm; returns a restore
    callable.  The LB reads these at construction (hedge deadline) and
    at breaker materialisation (probation knobs), so they must be in
    place before the fleet is built."""
    import os
    saved = {k: os.environ.get(k) for k in overrides}
    for k, v in overrides.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)

    def restore():
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
    return restore


def run_gray_bench(out: str, n_replicas: int = 3,
                   requests_per_arm: int = 90, lanes: int = 3,
                   delay_s: float = 0.35) -> None:
    """Gray-failure TTFT bench: one replica of a 3-replica fleet rots
    (every response chunk delayed ~`delay_s` by a seeded network proxy
    — alive, never failing, just slow) while client lanes stream
    through the LB.

    Two arms over the same request set:
      `no_ejection`     probation disabled (outlier threshold set
                        unreachable) and hedging off — the LB keeps
                        routing the degraded replica its full share,
                        so fleet p99 TTFT inherits the degradation.
      `ejection_hedge`  default probation knobs + TTFT hedging
                        (SKYTPU_LB_HEDGE_MS): a stream with no first
                        byte by the deadline is hedged to the
                        next-best replica, and the latency-outlier
                        track sheds the degraded replica to trickle
                        weight.

    The claim under measurement: hedging + probation cut fleet p99
    TTFT vs the no-ejection baseline, and rescue may NEVER change
    tokens — greedy outputs byte-identical per prompt across arms.
    Writes BENCH_SERVE_r09.json.
    """
    import os

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import FaultPlan, FaultSpec, InferConfig
    from skypilot_tpu.infer.chaos import ChaosFleet
    from skypilot_tpu.infer.engine import InferenceEngine
    from skypilot_tpu.models.llama import LlamaConfig

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    mc = LlamaConfig(name='graybench-t', vocab_size=101, hidden_size=32,
                     intermediate_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=128,
                     tie_embeddings=True, dtype='float32')
    cfg = InferConfig(num_slots=4, max_cache_len=64,
                      prefill_buckets=(8, 16, 32), max_new_tokens=16,
                      cache_dtype=jnp.float32, decode_steps=4,
                      kv_block_size=8, auto_prefix_cache=True)

    def make_engine():
        eng = InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0))
        eng.warmup()
        return eng

    prompts = [[(11 * i + 5 * j) % 97 + 1 for j in range(10)]
               for i in range(6)]

    def run_arm(name: str, env: dict):
        restore = _gray_env(env)
        fleet = None
        try:
            fleet = ChaosFleet(make_engine, n_replicas)
            fleet.start()
            plan = FaultPlan(seed=1, specs=[
                FaultSpec(site='net_degrade', prob=1.0,
                          delay_s=delay_s, jitter_s=0.05)])
            proxy = fleet.degrade_one(0, plan, seed=1)
            ttfts, outputs, errors = [], {}, []
            lock = threading.Lock()
            pending = list(range(requests_per_arm))

            def lane():
                while True:
                    with lock:
                        if not pending or errors:
                            return
                        i = pending.pop()
                    key = i % len(prompts)
                    try:
                        ttft, toks = _affinity_ttft_stream(
                            fleet.lb.port, prompts[key], max_new=8)
                    except Exception as e:  # pylint: disable=broad-except
                        with lock:
                            errors.append(f'req {i}: {e}')
                        return
                    with lock:
                        ttfts.append(ttft)
                        if outputs.setdefault(key, toks) != toks:
                            errors.append(f'divergence at prompt {key}')

            threads = [threading.Thread(target=lane, daemon=True)
                       for _ in range(lanes)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            if errors:
                raise RuntimeError(f'gray arm {name}: {errors[:3]}')
            stats = fleet.lb.lb_stats()
            vals = sorted(ttfts)

            def pct(p):
                return vals[min(len(vals) - 1, int(len(vals) * p))]

            row = {
                'arm': name,
                'requests': len(vals),
                'degraded_replicas': 1,
                'chunk_delay_s': delay_s,
                'ttft_p50_s': statistics.median(vals),
                'ttft_p95_s': pct(0.95),
                'ttft_p99_s': pct(0.99),
                'hedges': stats['hedges'],
                'hedge_wins': stats['hedge_wins'],
                'hedge_cancelled': stats['hedge_cancelled'],
                'probation_replicas': stats['probation_replicas'],
                'degraded_in_probation':
                    proxy.url in stats['probation_replicas'],
                'chunks_delayed': proxy.chunks_delayed,
            }
            print(json.dumps(row), flush=True)
            return row, outputs
        finally:
            if fleet is not None:
                fleet.stop()
            restore()

    arms = [
        ('no_ejection', {'SKYTPU_LB_PROBATION_K': '1e9',
                         'SKYTPU_LB_HEDGE_MS': None}),
        ('ejection_hedge', {'SKYTPU_LB_PROBATION_K': None,
                            'SKYTPU_LB_HEDGE_MS': '250'}),
    ]
    rows, outs = {}, {}
    for name, env in arms:
        print(f'-- gray arm={name}', flush=True)
        rows[name], outs[name] = run_arm(name, env)
    if outs['ejection_hedge'] != outs['no_ejection']:
        raise RuntimeError('greedy outputs diverged between gray arms')
    summary = {
        'p99_no_ejection_s': rows['no_ejection']['ttft_p99_s'],
        'p99_ejection_hedge_s': rows['ejection_hedge']['ttft_p99_s'],
        'p99_speedup':
            rows['no_ejection']['ttft_p99_s'] /
            rows['ejection_hedge']['ttft_p99_s'],
        'p99_improved':
            rows['ejection_hedge']['ttft_p99_s'] <
            rows['no_ejection']['ttft_p99_s'],
        'outputs_byte_identical': True,
    }
    print(json.dumps(summary), flush=True)
    try:
        doc = json.load(open(out))
    except (FileNotFoundError, ValueError):
        doc = {}
    doc['gray_failure'] = {'rows': list(rows.values()),
                           'summary': summary, 'model': 'tiny-cpu',
                           'measured_at': 'load_balancer_endpoint'}
    json.dump(doc, open(out, 'w'), indent=2)
    print(f'wrote {out}')


# ------------------------------------------------------ qos section


def _qos_stream(port: int, tokens, max_new: int, priority: str,
                tenant: str):
    """(ttft_s, output_tokens) for one prioritized stream via the LB."""
    conn = HTTPConnection('127.0.0.1', port, timeout=300)
    t0 = time.time()
    try:
        conn.request('POST', '/generate',
                     body=json.dumps({'tokens': tokens,
                                      'max_new_tokens': max_new,
                                      'stream': True,
                                      'priority': priority,
                                      'tenant_id': tenant}).encode(),
                     headers={'Content-Type': 'application/json'})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f'HTTP {resp.status}')
        buf, ttft, done = b'', None, None
        while done is None:
            chunk = resp.read1(65536)
            if not chunk:
                raise RuntimeError('stream ended without done event')
            buf += chunk
            while b'\n\n' in buf and done is None:
                ev, buf = buf.split(b'\n\n', 1)
                for line in ev.split(b'\n'):
                    if line.startswith(b'data: '):
                        msg = json.loads(line[6:])
                        if msg.get('done'):
                            done = msg
                        elif ttft is None and msg.get('tokens'):
                            ttft = time.time() - t0
        if done.get('finish_reason') not in ('length', 'eos'):
            raise RuntimeError(f'finish_reason={done.get("finish_reason")}'
                               f' error={done.get("error")!r}')
        return ttft if ttft is not None else time.time() - t0, \
            done['output_tokens']
    finally:
        conn.close()


def _batch_prompt(lane: int, seq: int, n: int = 96):
    return [(lane * 131 + seq * 37 + 5 * j) % 97 + 1 for j in range(n)]


def _interactive_prompt(i: int, n: int = 12):
    return [(i * 41 + 7 * j) % 97 + 1 for j in range(n)]


def run_qos_bench(out: str, interactive_n: int = 128,
                  batch_lanes: int = 4) -> None:
    """2x-overload QoS protection bench: one replica (2 decode slots,
    chunked prefill + radix), `batch_lanes` closed-loop batch-tenant
    lanes keeping 2x the slot count outstanding, and an open-loop
    interactive tenant measuring TTFT through the LB.

    Three arms: `uncontended` (interactive alone, the SLO floor),
    `fifo` (QoS off — interactive queues behind the flood), `qos`
    (WFQ + priority + chunk-boundary preemption).  The claim under
    measurement: interactive p99 TTFT under overload stays within
    1.5x uncontended while batch absorbs the queueing; and QoS only
    ever reorders — every completed greedy stream is byte-identical
    across arms."""
    import os

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig
    from skypilot_tpu.infer.chaos import ChaosFleet
    from skypilot_tpu.infer.engine import InferenceEngine
    from skypilot_tpu.models.llama import LlamaConfig

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    mc = LlamaConfig(name='qos-bench', vocab_size=101, hidden_size=64,
                     intermediate_size=128, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=256,
                     tie_embeddings=True, dtype='float32')

    def cfg(qos: bool) -> InferConfig:
        # Largest bucket 32 so the 96-token batch prompts take the
        # chunked path — that is what makes them preemptible.
        return InferConfig(num_slots=2, max_cache_len=192,
                           prefill_buckets=(16, 32), max_new_tokens=16,
                           cache_dtype=jnp.float32, decode_steps=4,
                           kv_block_size=16, kv_blocks=160,
                           prefill_chunk=16, auto_prefix_cache=True,
                           qos=qos)

    def run_arm(name: str, qos: bool, flood: bool):
        def mk():
            # Deterministic warmup (shared serve-plane helper) covers
            # the monolithic buckets, suffix buckets, and decode; the
            # qos-only residual classes below are the one shape family
            # it cannot enumerate.
            eng = InferenceEngine(mc, cfg(qos),
                                  rng=jax.random.PRNGKey(0))
            eng.warmup()
            return eng

        fleet = ChaosFleet(mk, 1)
        fleet.start()
        try:
            port = fleet.lb.port
            # Warm the qos-only resume path: a parked job resumes as a
            # radix suffix-only prefill, so prefix-sharing warm prompts
            # compile each residual class (16 -> bucket16, 32 ->
            # bucket32, 64 -> chunked) before any compile can land in a
            # measured TTFT.
            warm = [89] * 96
            _qos_stream(port, warm, 16, 'batch', 'warm')
            _qos_stream(port, warm[:80] + [23] * 16, 4, 'batch', 'warm')
            _qos_stream(port, warm[:64] + [29] * 32, 4, 'batch', 'warm')
            _qos_stream(port, warm[:32] + [31] * 64, 4, 'batch', 'warm')
            stop = threading.Event()
            batch_out, batch_err = {}, []

            def lane(lane_id: int):
                seq = 0
                while not stop.is_set():
                    key = (lane_id, seq)
                    try:
                        _, toks = _qos_stream(
                            port, _batch_prompt(lane_id, seq), 8,
                            'batch', 'bulk')
                        batch_out[key] = toks
                    except Exception as e:  # pylint: disable=broad-except
                        batch_err.append(f'{key}: {e}')
                        return
                    seq += 1

            lanes = []
            if flood:
                lanes = [threading.Thread(target=lane, args=(i,),
                                          daemon=True)
                         for i in range(batch_lanes)]
                for t in lanes:
                    t.start()
                time.sleep(0.5)       # flood reaches steady overload
            ttfts, inter_out = [], {}
            for i in range(interactive_n):
                ttft, toks = _qos_stream(port, _interactive_prompt(i),
                                         4, 'interactive', 'live')
                ttfts.append(ttft)
                inter_out[i] = toks
                time.sleep(0.05)
            stop.set()
            for t in lanes:
                t.join(timeout=120)
            if batch_err:
                raise RuntimeError(f'batch lane failed: {batch_err[:3]}')
            eng = fleet.replicas[0].server.engine
            vals = sorted(ttfts)
            row = {
                'arm': name,
                'interactive_requests': interactive_n,
                'batch_completed': len(batch_out),
                'ttft_p50_s': statistics.median(vals),
                'ttft_p95_s': vals[min(len(vals) - 1,
                                       int(len(vals) * 0.95))],
                'ttft_p99_s': vals[min(len(vals) - 1,
                                       int(len(vals) * 0.99))],
                'preemptions': eng.qos_stats['preemptions'],
                'sheds': eng.qos_stats['sheds'],
            }
            print(json.dumps(row), flush=True)
            return row, inter_out, batch_out
        finally:
            fleet.stop()

    rows, inter_outs, batch_outs = {}, {}, {}
    for name, qos, flood in [('uncontended', True, False),
                             ('fifo', False, True),
                             ('qos', True, True)]:
        print(f'-- qos arm={name}', flush=True)
        rows[name], inter_outs[name], batch_outs[name] = run_arm(
            name, qos, flood)
    # QoS reorders, never rewrites: greedy outputs byte-identical
    # across arms (interactive everywhere; batch on the common keys
    # the closed-loop lanes reached in both overload arms).
    for name in ('fifo', 'qos'):
        if inter_outs[name] != inter_outs['uncontended']:
            raise RuntimeError(
                f'interactive outputs diverged: {name} vs uncontended')
    common = set(batch_outs['fifo']) & set(batch_outs['qos'])
    for key in common:
        if batch_outs['fifo'][key] != batch_outs['qos'][key]:
            raise RuntimeError(f'batch outputs diverged at {key}')
    summary = {
        'overload': f'{2}x (closed-loop batch lanes = 2x decode slots)',
        'interactive_p99_vs_uncontended_fifo':
            rows['fifo']['ttft_p99_s'] / rows['uncontended']['ttft_p99_s'],
        'interactive_p99_vs_uncontended_qos':
            rows['qos']['ttft_p99_s'] / rows['uncontended']['ttft_p99_s'],
        'within_1_5x':
            rows['qos']['ttft_p99_s'] <=
            1.5 * rows['uncontended']['ttft_p99_s'],
        'batch_absorbed_queueing':
            rows['qos']['batch_completed'] > 0,
        'outputs_byte_identical': True,
        'batch_keys_compared': len(common),
    }
    print(json.dumps(summary), flush=True)
    try:
        doc = json.load(open(out))
    except (FileNotFoundError, ValueError):
        doc = {}
    doc['qos'] = {'rows': list(rows.values()), 'summary': summary,
                  'model': 'tiny-cpu',
                  'measured_at': 'load_balancer_endpoint'}
    json.dump(doc, open(out, 'w'), indent=2)
    print(f'wrote {out}')


# ---------------------------------------------------- batch section


def run_batch_bench(out: str, n_replicas: int = 2, n_rows: int = 48,
                    row_workers: int = 4,
                    interactive_slo_s: float = 2.0) -> None:
    """Bulk-inference goodput: the same `n_rows` greedy rows pushed
    through the fleet two ways, with an open-loop interactive tenant
    probing TTFT throughout —

      `online`       every row POSTed directly to the LB as an
                     interactive-class stream from `row_workers`
                     closed-loop lanes (what a user without the batch
                     plane would script)
      `batch_plane`  one `/v1/batches` job: journaled rows dispatched
                     as QoS batch-class requests by the
                     BatchCoordinator with the same worker width

    The claim under measurement: the batch plane sustains comparable
    fleet goodput (output tokens/s) while the interactive tenant's
    p99 TTFT holds the SLO — batch rows yield at the WFQ scheduler
    instead of queueing ahead of interactive work.  Greedy outputs
    must be byte-identical between arms.  Writes BENCH_SERVE_r10.json.
    """
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import InferConfig
    from skypilot_tpu.infer.chaos import ChaosFleet
    from skypilot_tpu.infer.engine import InferenceEngine
    from skypilot_tpu.models.llama import LlamaConfig
    from skypilot_tpu.serve.batch import BatchCoordinator

    os.environ.setdefault('SKYTPU_SERVE_LB_PROBE_INTERVAL', '0.2')
    mc = LlamaConfig(name='batch-bench', vocab_size=101, hidden_size=64,
                     intermediate_size=128, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=256,
                     tie_embeddings=True, dtype='float32')
    cfg = InferConfig(num_slots=4, max_cache_len=128,
                      prefill_buckets=(16, 32), max_new_tokens=16,
                      cache_dtype=jnp.float32, decode_steps=4,
                      kv_block_size=16, kv_blocks=160,
                      auto_prefix_cache=True, qos=True)

    def make_engine():
        eng = InferenceEngine(mc, cfg, rng=jax.random.PRNGKey(0))
        eng.warmup()
        return eng

    rows = [_batch_prompt(0, i, n=24) for i in range(n_rows)]
    max_new = 16

    def run_arm(name, drive):
        """drive(port) -> (outputs_by_idx, n_output_tokens); returns a
        bench row.  A fresh fleet per arm keeps radix state equal."""
        fleet = ChaosFleet(make_engine, n_replicas)
        fleet.start()
        try:
            port = fleet.lb.port
            # Warm the LB hop + interactive shape before measuring.
            _qos_stream(port, _interactive_prompt(0), 4,
                        'interactive', 'live')
            stop = threading.Event()
            ttfts, probe_err = [], []

            def prober():
                i = 0
                while not stop.is_set():
                    try:
                        ttft, _ = _qos_stream(
                            port, _interactive_prompt(i), 4,
                            'interactive', 'live')
                        ttfts.append(ttft)
                    except Exception as e:  # pylint: disable=broad-except
                        probe_err.append(str(e))
                        return
                    i += 1
                    time.sleep(0.05)

            pt = threading.Thread(target=prober, daemon=True)
            pt.start()
            t0 = time.time()
            outputs, out_tokens = drive(port)
            elapsed = time.time() - t0
            stop.set()
            pt.join(timeout=60)
            if probe_err:
                raise RuntimeError(f'{name} prober died: {probe_err[:1]}')
            vals = sorted(ttfts)
            row = {
                'arm': name,
                'rows': n_rows,
                'row_workers': row_workers,
                'elapsed_s': elapsed,
                'rows_per_s': n_rows / elapsed,
                'goodput_tokens_per_s': out_tokens / elapsed,
                'interactive_probes': len(vals),
                'interactive_ttft_p50_s': statistics.median(vals),
                'interactive_ttft_p99_s': vals[min(len(vals) - 1,
                                                   int(len(vals) * 0.99))],
            }
            print(json.dumps(row), flush=True)
            return row, outputs
        finally:
            fleet.stop()

    def drive_online(port):
        outputs, errors = {}, []
        lock = threading.Lock()
        pending = list(range(n_rows))

        def lane():
            while True:
                with lock:
                    if not pending or errors:
                        return
                    i = pending.pop()
                try:
                    _, toks = _qos_stream(port, rows[i], max_new,
                                          'interactive', 'bulk')
                except Exception as e:  # pylint: disable=broad-except
                    with lock:
                        errors.append(f'row {i}: {e}')
                    return
                with lock:
                    outputs[i] = toks

        lanes = [threading.Thread(target=lane, daemon=True)
                 for _ in range(row_workers)]
        for t in lanes:
            t.start()
        for t in lanes:
            t.join(timeout=600)
        if errors:
            raise RuntimeError(f'online arm failed: {errors[:3]}')
        return outputs, sum(len(t) for t in outputs.values())

    def drive_batch(port):
        with tempfile.TemporaryDirectory() as tmp:
            coord = BatchCoordinator(
                os.path.join(tmp, 'batch_journal.jsonl'), port,
                spool_dir=os.path.join(tmp, 'spool'),
                row_workers=row_workers)
            try:
                jid = coord.submit(rows, max_new,
                                   completion_window_s=600.0,
                                   job_id='bench')
                if not coord.join(jid, timeout=600):
                    raise RuntimeError(
                        f'batch job never finished: {coord.status(jid)}')
                st = coord.status(jid)
                if st['state'] != 'done':
                    raise RuntimeError(f'batch job failed: {st}')
                outputs = {}
                with open(coord.result_path(jid)) as fh:
                    for line in fh:
                        rec = json.loads(line)
                        outputs[rec['row']] = rec['output_tokens']
                return outputs, sum(len(t) for t in outputs.values())
            finally:
                coord.stop()

    results = {}
    for name, drive in [('online', drive_online),
                        ('batch_plane', drive_batch)]:
        print(f'-- batch arm={name}', flush=True)
        results[name] = run_arm(name, drive)
    if results['batch_plane'][1] != results['online'][1]:
        raise RuntimeError('greedy outputs diverged between the online '
                           'and batch-plane arms')
    on, bp = results['online'][0], results['batch_plane'][0]
    summary = {
        'interactive_slo_s': interactive_slo_s,
        'goodput_ratio_batch_vs_online':
            bp['goodput_tokens_per_s'] / on['goodput_tokens_per_s'],
        'interactive_p99_online_s': on['interactive_ttft_p99_s'],
        'interactive_p99_batch_s': bp['interactive_ttft_p99_s'],
        'interactive_p99_within_slo':
            bp['interactive_ttft_p99_s'] <= interactive_slo_s,
        'outputs_byte_identical': True,
    }
    print(json.dumps(summary), flush=True)
    try:
        doc = json.load(open(out))
    except (FileNotFoundError, ValueError):
        doc = {}
    doc['batch_plane'] = {'rows': [on, bp], 'summary': summary,
                          'model': 'tiny-cpu',
                          'measured_at': 'load_balancer_endpoint'}
    json.dump(doc, open(out, 'w'), indent=2)
    print(f'wrote {out}')


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--qps', action='append', type=float, default=[])
    parser.add_argument('--requests-per-qps', type=int, default=48,
                        help='num_requests = qps * this')
    parser.add_argument('--num-slots', type=int, default=None)
    parser.add_argument('--decode-steps', type=int, default=None)
    parser.add_argument('--profile', default=None,
                        choices=['latency', 'throughput'],
                        help='replica operating point (infer serve '
                             '--profile); explicit --num-slots/'
                             '--decode-steps still win')
    parser.add_argument('--max-ttft', type=float, default=None,
                        help='replica admission bound (s); sheds count '
                             'in the sweep rows')
    parser.add_argument('--max-queue', type=int, default=None,
                        help='replica hard backlog cap (requests)')
    parser.add_argument('--service-name', default='lbbench')
    parser.add_argument('--out', default=None)
    parser.add_argument('--keep-up', action='store_true',
                        help='leave the service running afterwards')
    parser.add_argument('--endpoint', default=None,
                        help='reuse an existing endpoint (skip serve up)')
    parser.add_argument('--failover', action='store_true',
                        help='run the replica-failover latency section '
                             '(in-process fleet, CPU-friendly)')
    parser.add_argument('--failover-iters', type=int, default=6)
    parser.add_argument('--affinity', action='store_true',
                        help='run the prefix-affinity routing TTFT '
                             'sweep (in-process fleet, CPU-friendly)')
    parser.add_argument('--affinity-replicas', type=int, default=3)
    parser.add_argument('--affinity-groups', type=int, default=8)
    parser.add_argument('--affinity-per-group', type=int, default=6)
    parser.add_argument('--gray', action='store_true',
                        help='run the gray-failure ejection/hedging '
                             'TTFT section (in-process fleet, '
                             'CPU-friendly)')
    parser.add_argument('--gray-requests', type=int, default=90,
                        help='requests per gray arm (p99 needs enough '
                             'draws)')
    parser.add_argument('--qos', action='store_true',
                        help='run the 2x-overload QoS protection '
                             'section (in-process fleet, CPU-friendly)')
    parser.add_argument('--qos-interactive', type=int, default=128,
                        help='interactive sample count (p99 needs '
                             'enough draws to not be the single max)')
    parser.add_argument('--qos-batch-lanes', type=int, default=4)
    parser.add_argument('--batch', action='store_true',
                        help='run the batch-plane vs online goodput '
                             'section (in-process fleet, CPU-friendly)')
    parser.add_argument('--batch-rows', type=int, default=48)
    args = parser.parse_args()
    if args.batch:
        run_batch_bench(args.out or 'BENCH_SERVE_r10.json',
                        n_rows=args.batch_rows)
        return
    if args.failover:
        run_failover_bench(args.failover_iters,
                           args.out or 'BENCH_SERVE_r06.json')
        return
    if args.gray:
        run_gray_bench(args.out or 'BENCH_SERVE_r09.json',
                       requests_per_arm=args.gray_requests)
        return
    if args.qos:
        run_qos_bench(args.out or 'BENCH_SERVE_r08.json',
                      interactive_n=args.qos_interactive,
                      batch_lanes=args.qos_batch_lanes)
        return
    if args.affinity:
        run_affinity_bench(args.out or 'BENCH_SERVE_r07.json',
                           n_replicas=args.affinity_replicas,
                           groups=args.affinity_groups,
                           per_group=args.affinity_per_group)
        return
    qps_list = args.qps or [2.0, 3.5]

    from skypilot_tpu import Resources, Task, state
    from skypilot_tpu.serve import core as serve_core

    endpoint = args.endpoint
    name = args.service_name
    if endpoint is None:
        state.set_enabled_clouds(['local'])
        num_slots = args.num_slots if args.num_slots is not None else \
            (None if args.profile else 48)
        run_cmd = (
            'python -m skypilot_tpu.cli infer serve '
            '--model llama2-7b --weight-dtype int8 --cache-dtype fp8 '
            + (f'--profile {args.profile} ' if args.profile else '')
            + (f'--num-slots {num_slots} '
               if num_slots is not None else '')
            + (f'--decode-steps {args.decode_steps} '
               if args.decode_steps is not None else '')
            + '--max-cache-len 512 '
            + (f'--max-ttft {args.max_ttft} '
               if args.max_ttft is not None else '')
            + (f'--max-queue {args.max_queue} '
               if args.max_queue is not None else '')
            + '--port $SKYTPU_SERVE_REPLICA_PORT')
        from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec
        task = Task('llama-serve-bench', run=run_cmd)
        task.set_resources(Resources(cloud='local'))
        task.set_service(SkyTpuServiceSpec.from_yaml_config({
            'readiness_probe': {'path': '/health',
                                'initial_delay_seconds': 1800},
            'replica_policy': {'min_replicas': 1, 'max_replicas': 1},
            'port': 8100,
        }))
        name, endpoint = serve_core.up(task, service_name=name)
        print(f'service {name} at {endpoint}; waiting for READY...',
              flush=True)
        deadline = time.time() + 1800
        while time.time() < deadline:
            svcs = serve_core.status([name])
            if svcs and svcs[0]['status'] == 'READY':
                break
            time.sleep(5)
        else:
            raise TimeoutError('replica never became READY')
    print(f'driving load at {endpoint}', flush=True)
    # Warm the serving path (compile happened at replica start; this
    # warms the LB connection + prefill bucket).  The LB's replica list
    # syncs on an interval, so READY status can precede LB routability —
    # retry the warm request until the path is live.
    deadline = time.time() + 300
    while True:
        try:
            _post_stream(endpoint, list(range(4, 4 + PROMPT_LEN)), 4)
            break
        except Exception as e:  # pylint: disable=broad-except
            if time.time() > deadline:
                raise
            print(f'warm request not routable yet ({e}); retrying',
                  flush=True)
            time.sleep(5)

    rows = []
    for qps in qps_list:
        n = max(int(qps * args.requests_per_qps), 16)
        print(f'-- qps {qps} ({n} requests)', flush=True)
        row = run_sweep_row(endpoint, qps, n)
        if args.profile:
            row['profile'] = args.profile
        print(json.dumps(row), flush=True)
        rows.append(row)

    if args.out:
        try:
            doc = json.load(open(args.out))
        except (FileNotFoundError, ValueError):
            doc = {}
        doc.setdefault('serve_plane_sweep', [])
        doc['serve_plane_sweep'] += rows
        json.dump(doc, open(args.out, 'w'), indent=2)
        print(f'wrote {args.out}')
    if endpoint and not args.keep_up and args.endpoint is None:
        serve_core.down([name])


if __name__ == '__main__':
    main()
