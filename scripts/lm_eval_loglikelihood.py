"""Minimal lm-eval-harness loglikelihood client for the OpenAI API.

Implements exactly the request pattern lm-eval's OpenAI adapter
(lm_eval/models/openai_completions.py upstream) uses for
`loglikelihood` scoring — the path HellaSwag/ARC/LAMBADA-style
multiple-choice tasks take:

    POST /v1/completions
        prompt      = context_tokens + continuation_tokens
        max_tokens  = 0          (score only, generate nothing)
        echo        = True       (return prompt logprobs)
        logprobs    = 1          (chosen + argmax alternative)

and sums log P(continuation | context) over the continuation
positions; `is_greedy` is whether every continuation token was the
model's argmax (needed for tasks reporting exact-match greedy
accuracy).

The in-repo inference server serves this contract (engine
want_prompt_logprobs path); lm-eval-harness itself is not vendored, so
this client doubles as the compatibility artifact: anything it can
score, the real harness can.  Usage:

    python scripts/lm_eval_loglikelihood.py \
        --endpoint http://HOST:8100 --context 5,6,7 \
        --choices 8,9 10,11 12
"""
import argparse
import json
import urllib.request
from typing import List, Sequence, Tuple


def loglikelihood(endpoint: str, context: Sequence[int],
                  continuation: Sequence[int],
                  model: str = None,
                  timeout: float = 120.0) -> Tuple[float, bool]:
    """(sum of continuation logprobs, is_greedy) for one (context,
    continuation) pair — the lm-eval `loglikelihood` primitive."""
    context = [int(t) for t in context]
    continuation = [int(t) for t in continuation]
    if not context or not continuation:
        raise ValueError('context and continuation must be non-empty')
    body = {
        'prompt': context + continuation,
        'max_tokens': 0,
        'echo': True,
        'logprobs': 1,
        'temperature': 0,
    }
    if model is not None:
        body['model'] = model
    req = urllib.request.Request(
        endpoint.rstrip('/') + '/v1/completions',
        data=json.dumps(body).encode(),
        headers={'Content-Type': 'application/json'})
    out = json.loads(urllib.request.urlopen(req, timeout=timeout).read())
    lp = out['choices'][0]['logprobs']
    token_lps = lp['token_logprobs']
    tops = lp['top_logprobs']
    n = len(continuation)
    assert len(token_lps) == len(context) + n, (
        'server must echo one logprob per prompt position')
    cont_lps = token_lps[-n:]
    total = float(sum(cont_lps))
    # is_greedy: at every continuation position the chosen token's
    # logprob equals the argmax alternative's (argmax == chosen).
    is_greedy = all(
        tops[len(context) + i] is not None and
        abs(max(tops[len(context) + i].values()) - cont_lps[i]) < 1e-6
        for i in range(n))
    return total, is_greedy


def loglikelihood_rolling(endpoint: str, tokens: Sequence[int],
                          max_context: int = 2048,
                          model: str = None) -> float:
    """Sum log P(token_t | window) over an arbitrarily long stream —
    lm-eval's `loglikelihood_rolling` primitive (wikitext-style
    perplexity).  The stream is scored in non-overlapping windows of
    `max_context` tokens: each window is one echo+logprobs+max_tokens=0
    request whose FIRST position is unscored (no context), exactly how
    the upstream harness rolls windows with disjoint scoring.  Returns
    the total loglikelihood of tokens[1:] (convert to perplexity via
    exp(-total / (len(tokens) - 1)))."""
    tokens = [int(t) for t in tokens]
    if len(tokens) < 2:
        raise ValueError('need at least 2 tokens to score')
    total = 0.0
    pos = 1                    # next position to score
    while pos < len(tokens):
        # Window carries ONE token of left context (position pos-1),
        # so every token from index 1 is scored exactly once — the
        # upstream harness's disjoint-window rolling.
        window = tokens[pos - 1:pos - 1 + max_context]
        body = {'prompt': window, 'max_tokens': 0, 'echo': True,
                'logprobs': 1, 'temperature': 0}
        if model is not None:
            body['model'] = model
        req = urllib.request.Request(
            endpoint.rstrip('/') + '/v1/completions',
            data=json.dumps(body).encode(),
            headers={'Content-Type': 'application/json'})
        out = json.loads(urllib.request.urlopen(req, timeout=300).read())
        lps = out['choices'][0]['logprobs']['token_logprobs']
        assert lps[0] is None and len(lps) == len(window)
        total += float(sum(lps[1:]))
        pos += len(window) - 1
    return total


def rank_choices(endpoint: str, context: Sequence[int],
                 choices: Sequence[Sequence[int]],
                 model: str = None) -> List[int]:
    """Choice indices best-first by loglikelihood — the multiple-choice
    accuracy primitive (argmax = the model's answer)."""
    scores = [
        loglikelihood(endpoint, context, c, model=model)[0]
        for c in choices
    ]
    return sorted(range(len(choices)), key=lambda i: -scores[i])


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--endpoint', required=True)
    parser.add_argument('--context', required=True,
                        help='comma-separated token ids')
    parser.add_argument('--choices', nargs='+', required=True,
                        help='one comma-separated token list per choice')
    parser.add_argument('--model', default=None)
    args = parser.parse_args()
    context = [int(t) for t in args.context.split(',')]
    choices = [[int(t) for t in c.split(',')] for c in args.choices]
    rows = []
    for i, cont in enumerate(choices):
        score, greedy = loglikelihood(args.endpoint, context, cont,
                                      model=args.model)
        rows.append({'choice': i, 'loglikelihood': score,
                     'is_greedy': greedy})
    # Rank from the scores in hand — no second scoring pass.
    ranked = sorted(range(len(rows)),
                    key=lambda i: -rows[i]['loglikelihood'])
    print(json.dumps({'scores': rows, 'ranking': ranked,
                      'answer': ranked[0]}))


if __name__ == '__main__':
    main()
