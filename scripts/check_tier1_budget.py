#!/usr/bin/env python3
"""Tier-1 duration guard: parse a pytest log run with --durations=N,
print the slowest tests, and FAIL when the recorded suite time pushes
past the tier-1 timeout budget.

The tier-1 wrapper (scripts/run_tier1.sh) runs the suite with
`--durations=15 | tee <log>` and then this checker over the log.  The
point is catching the failure mode where a PR's *new tests* quietly eat
the fixed 870 s CI window — every added second displaces tail-of-suite
tests from the window, which then read as "skipped" rather than as the
regression they are.  The checker reports:

- the suite wall time (pytest's trailing `in NNN.NNs` summary), judged
  against the budget with a headroom margin (default 10%: a suite at
  95% of the window WILL time out on a noisy runner);
- the slowest-test table so the offender is named in the failure.

- per-file rollups of the recorded duration rows, so a file that grew
  across several tests is named even when no single test tops the
  table;
- `--require <file>`: tier-1 files that MUST appear in the log — a new
  test file silently dropped from the window (collection error, bad
  marker, renamed path) fails the guard instead of passing by absence.
  Required paths are validated against the test files that actually
  exist on disk (skypilot_tpu.analysis.walker — the same discovery
  skycheck uses, so __pycache__ artifacts can't satisfy a typo), and a
  typo'd --require fails loudly instead of failing every run.
- `--extra-seconds LABEL:SECONDS`: wall time spent by non-pytest tier-1
  steps that share the CI window (e.g. a bench dryrun) — added to the
  suite time before the budget verdict so the pytest budget shrinks by
  exactly what the other steps consumed.
- `--skycheck-json FILE`: the machine output of
  `scripts/skycheck.py --json FILE` — every analysis pass is charged
  individually (label `skycheck.<pass>`) instead of as one opaque
  lump, so the pass that grew names itself in this report.

Usage:
    python scripts/check_tier1_budget.py /tmp/_t1.log \
        [--budget 870] [--margin 0.10] [--top 15] \
        [--require tests/test_radix.py ...] \
        [--skycheck-json /tmp/_skycheck.json] \
        [--extra-seconds bench_dryrun:2.1]

Exit codes: 0 within budget, 1 over budget (or the run itself timed
out, which a missing summary line implies), 2 unreadable log or bad
arguments.
"""
import argparse
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from skypilot_tpu.analysis.walker import iter_py_files  # noqa: E402

# `1.23s call tests/test_x.py::test_y` rows from --durations=N.
_DURATION_ROW = re.compile(
    r'^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)')
# Trailing summary: `==== 12 passed, 3 failed in 512.34s ====` (pytest
# prints `in 512.34s (0:08:32)` past the hour; match the seconds form).
_SUMMARY = re.compile(r'\bin (\d+(?:\.\d+)?)s\b')


def parse_log(text: str):
    """Returns (wall_seconds or None, [(seconds, phase, test), ...])."""
    durations = []
    wall = None
    for line in text.splitlines():
        m = _DURATION_ROW.match(line)
        if m:
            durations.append((float(m.group(1)), m.group(2), m.group(3)))
        m = _SUMMARY.search(line)
        if m:
            wall = float(m.group(1))   # keep the LAST summary line
    durations.sort(reverse=True)
    return wall, durations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument('log', help='pytest output (run with --durations=15)')
    ap.add_argument('--budget', type=float, default=870.0,
                    help='tier-1 wall-clock timeout in seconds')
    ap.add_argument('--margin', type=float, default=0.10,
                    help='headroom fraction: fail past '
                         'budget*(1-margin), not just past the cliff')
    ap.add_argument('--top', type=int, default=15,
                    help='slowest tests to print')
    ap.add_argument('--require', action='append', default=[],
                    metavar='FILE',
                    help='test file that must show up in the log '
                         '(repeatable); guards tier-1 files against '
                         'silently dropping out of the window')
    ap.add_argument('--extra-seconds', action='append', default=[],
                    metavar='LABEL:SECONDS',
                    help='non-pytest wall time sharing the window '
                         '(repeatable), e.g. bench_dryrun:2.1; added '
                         'to the suite time for the budget verdict')
    ap.add_argument('--skycheck-json', default=None, metavar='FILE',
                    help='skycheck --json output: charge each analysis '
                         'pass its own measured seconds')
    args = ap.parse_args(argv)
    extras = []
    for spec in args.extra_seconds:
        label, sep, secs = spec.partition(':')
        try:
            extras.append((label, float(secs)))
        except ValueError:
            print(f'check_tier1_budget: bad --extra-seconds {spec!r} '
                  '(want LABEL:SECONDS)')
            return 2
    if args.skycheck_json:
        try:
            with open(args.skycheck_json, encoding='utf-8') as f:
                sky = json.load(f)
            for name, info in sorted(sky.get('passes', {}).items()):
                extras.append((f'skycheck.{name}',
                               float(info['seconds'])))
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f'check_tier1_budget: bad --skycheck-json '
                  f'{args.skycheck_json!r}: {e}')
            return 2
    on_disk = set(iter_py_files(_REPO, subdirs=['tests']))
    unknown = [req for req in args.require if req not in on_disk]
    if unknown:
        print('check_tier1_budget: --require path(s) not found on disk '
              '(typo? renamed?): ' + ', '.join(unknown))
        return 2
    try:
        with open(args.log, encoding='utf-8', errors='replace') as f:
            text = f.read()
    except OSError as e:
        print(f'check_tier1_budget: cannot read {args.log}: {e}')
        return 2
    wall, durations = parse_log(text)
    if durations:
        print(f'slowest {min(args.top, len(durations))} test phases:')
        for secs, phase, test in durations[:args.top]:
            print(f'  {secs:8.2f}s  {phase:<8}  {test}')
        by_file = {}
        for secs, _, test in durations:
            by_file[test.split('::')[0]] = \
                by_file.get(test.split('::')[0], 0.0) + secs
        print('per-file totals over the recorded rows:')
        for path, secs in sorted(by_file.items(), key=lambda kv: -kv[1]):
            print(f'  {secs:8.2f}s  {path}')
    else:
        print('no --durations rows in the log (run pytest with '
              '--durations=15)')
    missing = [req for req in args.require if req not in text]
    if missing:
        print('FAIL: required tier-1 file(s) absent from the log '
              '(collection error, bad marker, or renamed path?): '
              + ', '.join(missing))
        return 1
    if wall is None:
        # No `in NNNs` summary: pytest never finished — the timeout
        # already fired.  That IS the over-budget condition.
        print(f'FAIL: no pytest summary line in {args.log} — the suite '
              f'did not finish inside the {args.budget:.0f}s budget')
        return 1
    total = wall + sum(secs for _, secs in extras)
    if extras:
        spent = ', '.join(f'{label} {secs:.1f}s' for label, secs in extras)
        print(f'non-pytest tier-1 steps: {spent}')
    limit = args.budget * (1.0 - args.margin)
    verdict = 'OK' if total <= limit else 'FAIL'
    print(f'{verdict}: suite took {wall:.1f}s'
          + (f' (+{total - wall:.1f}s non-pytest = {total:.1f}s)'
             if extras else '')
          + f'; budget {args.budget:.0f}s '
          f'(fail threshold {limit:.0f}s = {args.margin:.0%} headroom)')
    return 0 if total <= limit else 1


if __name__ == '__main__':
    sys.exit(main())
